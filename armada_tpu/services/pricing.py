"""Bid-price provider: per-(queue, band, pool) bids feeding market mode.

The reference's pricing layer (internal/scheduler/pricing/{types,bid_service,
bid_price,client}.go and pkg/bidstore) supplies each job's bid from a
periodically refreshed snapshot keyed by (queue, price band): jobs carry a
price band (an annotation-sized enum, bidstore/util.go:21-44), the provider
returns a `BidPriceSnapshot`, and the scheduler re-prices exactly the jobs
whose (queue, band) key changed between snapshots (scheduler.go:540-585).

Re-designed here as plain host-side data flow: the provider interface is a
single `get_bid_prices()` returning an immutable snapshot; diffing and job
re-pricing are pure functions over the jobdb, so they compose with the
event-sourced restart story (bids are NOT event-sourced — like the
reference, a restarted scheduler simply re-fetches from the provider).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field

from ..snapshot.round import NON_PREEMPTIBLE_RUNNING_PRICE  # re-export

# Price bands (pkg/bidstore PriceBand; short names from bidstore/util.go).
PRICE_BAND_UNSPECIFIED = 0
PRICE_BANDS: dict[str, int] = {
    "None": PRICE_BAND_UNSPECIFIED,
    **{chr(ord("A") + i): i + 1 for i in range(8)},  # A..H = 1..8
}
PRICE_BAND_NAMES = {v: k for k, v in PRICE_BANDS.items()}

PRICE_BAND_ANNOTATION = "armadaproject.io/priceBand"

__all__ = [
    "Bid",
    "BidPriceSnapshot",
    "ExternalBidPriceService",
    "LocalBidPriceService",
    "NON_PREEMPTIBLE_RUNNING_PRICE",  # single source: snapshot/round.py
    "NoopBidPriceProvider",
    "PRICE_BANDS",
    "job_price_band",
    "refresh_job_bids",
]


def job_price_band(spec) -> int:
    """Band a submitted job bid into (jobdb job.GetPriceBand); unknown or
    absent annotations fall back to UNSPECIFIED rather than erroring — a
    malformed job must not break the pricing refresh."""
    raw = str(spec.annotations.get(PRICE_BAND_ANNOTATION, "None"))
    return PRICE_BANDS.get(raw, PRICE_BANDS.get(raw.upper(), PRICE_BAND_UNSPECIFIED))


@dataclass(frozen=True)
class Bid:
    """Queued/running phase bids (pricing.Bid)."""

    queued: float = 0.0
    running: float = 0.0


@dataclass(frozen=True)
class BidPriceSnapshot:
    """One provider fetch (pricing.BidPriceSnapshot): bids keyed by
    (queue, band) -> {pool: Bid}. Two snapshots with the same id hold
    identical bids."""

    id: str
    timestamp: float
    bids: dict = field(default_factory=dict)  # {(queue, band): {pool: Bid}}
    resource_units: dict = field(default_factory=dict)  # {pool: {res: qty}}

    def get_price(self, queue: str, band: int):
        return self.bids.get((queue, band))

    def changed_price_keys(self, previous: "BidPriceSnapshot | None") -> set:
        """Keys added, removed, or re-priced vs `previous`
        (types.go ChangedPriceKeys)."""
        prev = previous.bids if previous is not None else {}
        changed = {k for k, v in self.bids.items() if prev.get(k) != v}
        changed |= {k for k in prev if k not in self.bids}
        return changed


class NoopBidPriceProvider:
    """Market mode off / no provider configured (pricing.NoopBidPriceProvider)."""

    def get_bid_prices(self) -> BidPriceSnapshot:
        return BidPriceSnapshot(id=uuid.uuid4().hex, timestamp=time.time())


class LocalBidPriceService:
    """Deterministic in-process provider (pricing.LocalBidPriceService):
    every queue bids band+1 in every pool, both phases — enough to exercise
    the full market path without an external bid store."""

    def __init__(self, pools: list[str], queues):
        self.pools = list(pools)
        self._queues = queues  # callable -> iterable of queue names

    def get_bid_prices(self) -> BidPriceSnapshot:
        bids = {}
        for queue in self._queues():
            for band in PRICE_BANDS.values():
                bids[(queue, band)] = {
                    pool: Bid(float(band) + 1.0, float(band) + 1.0)
                    for pool in self.pools
                }
        return BidPriceSnapshot(
            id=uuid.uuid4().hex, timestamp=time.time(), bids=bids
        )


class ExternalBidPriceService:
    """Adapter over a remote bid store (pricing.ExternalBidPriceService +
    bidstore client). `client` is any object with retrieve_bids() returning

        {"queue_bids": {queue: {pool: {band(int|str): {"queued": x,
                                                       "running": y}}}},
         "fallback":   {queue: {pool: {"queued": x, "running": y}}},
         "pool_resource_units": {pool: {resource: qty}}}

    Bands absent from a queue/pool fall back to the queue's fallback bids
    per phase (bid_service.go:124-141 getPrice). Transport errors propagate
    to the caller, which keeps the previous snapshot."""

    def __init__(self, client):
        self.client = client

    def get_bid_prices(self) -> BidPriceSnapshot:
        resp = self.client.retrieve_bids()
        bids = {}
        fallback = resp.get("fallback", {})
        for queue, pool_bids in resp.get("queue_bids", {}).items():
            for band in PRICE_BANDS.values():
                per_pool = {}
                for pool, band_bids in pool_bids.items():
                    # Probe int key, JSON-stringified int key (this repo's
                    # gRPC encoding stringifies int dict keys), then name.
                    bb = band_bids.get(
                        band,
                        band_bids.get(
                            str(band), band_bids.get(PRICE_BAND_NAMES[band])
                        ),
                    )
                    fb = fallback.get(queue, {}).get(pool, {})
                    queued = _phase(bb, fb, "queued")
                    running = _phase(bb, fb, "running")
                    if queued is not None or running is not None:
                        per_pool[pool] = Bid(queued or 0.0, running or 0.0)
                if per_pool:
                    bids[(queue, band)] = per_pool
        return BidPriceSnapshot(
            id=resp.get("id", uuid.uuid4().hex),
            timestamp=time.time(),
            bids=bids,
            resource_units={
                p: dict(r)
                for p, r in resp.get("pool_resource_units", {}).items()
            },
        )


def _phase(band_bid, fallback, phase: str):
    if band_bid is not None and phase in band_bid:
        return float(band_bid[phase])
    if fallback and phase in fallback:
        return float(fallback[phase])
    return None


def refresh_job_bids(
    jobdb,
    snapshot: BidPriceSnapshot,
    previous: BidPriceSnapshot | None,
    new_job_ids=(),
) -> int:
    """Apply a new snapshot to the job store: only jobs whose (queue, band)
    price actually changed are touched (scheduler.go:542-577). Returns the
    number of jobs re-priced. Bids are written as {pool: (queued, running)}
    pairs via fresh immutable specs through a write txn (never mutated in
    place — the spec object is shared with API threads serializing job
    details); JobSpec.bid_price resolves the phase at snapshot build time."""
    changed = snapshot.changed_price_keys(previous)
    if not changed and not new_job_ids:
        return 0
    txn = jobdb.write_txn()
    changed_queues = {queue for queue, _ in changed}
    # Indexed walk: queued jobs per changed queue + the leased set — never
    # the whole store (terminal jobs need no re-pricing). `new_job_ids`
    # (jobs submitted since the last refresh, tracked by the caller) are
    # priced from the current snapshot regardless of the diff, or a job
    # arriving under stable prices would sort at bid 0 forever.
    candidates = [
        job
        for queue in changed_queues
        for job in txn.queued_jobs(queue, sort=False)
    ] + [job for job in txn.leased_jobs() if job.queue in changed_queues]
    seen = {job.id for job in candidates}
    for job_id in new_job_ids:
        job = txn.get(job_id)
        if job is not None and not job.state.terminal and job.id not in seen:
            candidates.append(job)
    updated = []
    for job in candidates:
        key = (job.queue, job_price_band(job.spec))
        if key not in changed and job.spec.bid_prices:
            continue
        bids = snapshot.bids.get(key)
        if bids is None:
            # Key vanished from the new snapshot: keep the stale price
            # (the reference leaves these in place too, scheduler.go:565).
            continue
        updated.append(
            job.with_(
                spec=job.spec.with_(
                    bid_prices={
                        pool: (bid.queued, bid.running)
                        for pool, bid in bids.items()
                    }
                )
            )
        )
    if updated:
        txn.upsert(*updated)
    txn.commit()
    return len(updated)
