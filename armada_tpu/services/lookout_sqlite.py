"""Persistent lookout store: the SQLite-backed materialized view.

The reference lookout keeps its denormalized job/run rows in Postgres
(internal/lookout/, lookoutingester/lookoutdb/insertion.go) with a
retention pruner (internal/lookout/pruner/pruner.go); restarts resume
from the rows already on disk. The round-4 view here was RAM-only dicts —
at "millions of jobs" it exceeds memory and restarts replay everything.

`SqliteLookoutStore` is interface-compatible with the in-memory
`LookoutStore` (all_rows/get/get_run/materialize/prune/sync/lag_events),
so `QueryApi` and the UI run unchanged against either. Event application
REUSES `LookoutStore._apply` verbatim over a lazy row mapping: rows are
faulted in from SQLite per sync batch, mutated as plain `LookoutRow`
objects by the shared code, and upserted together with the ingest cursor
in ONE transaction — crash-consistent, and a reopened store resumes from
its cursor without replaying the log (meta table). WAL mode keeps UI
reads non-blocking under ingest.

Schema (denormalized like lookoutdb: one row per job, runs embedded,
plus a run_id -> job_id drilldown index):

  job(job_id PK, queue, jobset, state, priority, priority_class,
      requests JSON, annotations JSON, submitted, last_transition,
      cancelled, error, error_category, runs JSON)
  run_index(run_id PK, job_id)
  meta(key PK, value)           -- 'cursor'
"""

from __future__ import annotations

import json
import sqlite3
import threading
from dataclasses import asdict

from .lookout_ingester import LookoutRow, LookoutRun, LookoutStore

_TERMINAL = ("succeeded", "failed", "cancelled", "preempted")
_ACTIVE = ("queued", "leased", "pending", "running")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS job(
  job_id TEXT PRIMARY KEY, queue TEXT NOT NULL, jobset TEXT NOT NULL,
  state TEXT NOT NULL, priority INTEGER, priority_class TEXT,
  requests TEXT, annotations TEXT, submitted REAL, last_transition REAL,
  cancelled REAL, error TEXT, error_category TEXT, runs TEXT);
CREATE INDEX IF NOT EXISTS job_queue_submitted ON job(queue, submitted, job_id);
CREATE INDEX IF NOT EXISTS job_jobset ON job(queue, jobset);
CREATE INDEX IF NOT EXISTS job_state ON job(state);
CREATE INDEX IF NOT EXISTS job_submitted ON job(submitted, job_id);
CREATE INDEX IF NOT EXISTS job_last_transition ON job(last_transition, job_id);
CREATE TABLE IF NOT EXISTS run_index(run_id TEXT PRIMARY KEY, job_id TEXT);
CREATE INDEX IF NOT EXISTS run_job ON run_index(job_id);
CREATE TABLE IF NOT EXISTS meta(key TEXT PRIMARY KEY, value TEXT);
"""

_COLS = (
    "job_id queue jobset state priority priority_class requests annotations "
    "submitted last_transition cancelled error error_category runs"
).split()


def _row_to_record(row: LookoutRow) -> tuple:
    return (
        row.job_id,
        row.queue,
        row.jobset,
        row.state,
        row.priority,
        row.priority_class,
        json.dumps(row.requests),
        json.dumps(row.annotations),
        row.submitted,
        row.last_transition,
        row.cancelled,
        row.error,
        row.error_category,
        json.dumps([asdict(r) for r in row.runs]),
    )


def _record_to_row(rec) -> LookoutRow:
    return LookoutRow(
        job_id=rec[0],
        queue=rec[1],
        jobset=rec[2],
        state=rec[3],
        priority=rec[4],
        priority_class=rec[5],
        requests=json.loads(rec[6] or "{}"),
        annotations=json.loads(rec[7] or "{}"),
        submitted=rec[8],
        last_transition=rec[9],
        cancelled=rec[10],
        error=rec[11],
        error_category=rec[12],
        runs=[LookoutRun(**r) for r in json.loads(rec[13] or "[]")],
    )


class _LazyRowMap:
    """dict-ish view over the job table for LookoutStore._apply: rows
    fault in from SQLite, and everything touched within a sync batch is
    flushed back (mutations happen in place on the objects, so touched ==
    potentially dirty)."""

    def __init__(self, store: "SqliteLookoutStore"):
        self.store = store
        self.cache: dict[str, LookoutRow] = {}
        # Known-missing ids within the current sync batch (prefetch
        # misses + freshly submitted ids): membership checks answer from
        # memory instead of a per-event SELECT.
        self.absent: set[str] = set()

    def get(self, job_id, default=None):
        if job_id in self.cache:
            return self.cache[job_id]
        if job_id in self.absent:
            return default
        row = self.store._load_row(job_id)
        if row is not None:
            self.cache[job_id] = row
            return row
        self.absent.add(job_id)
        return default

    def __contains__(self, job_id):
        return self.get(job_id) is not None

    def __setitem__(self, job_id, row):
        self.cache[job_id] = row
        self.absent.discard(job_id)


class _LazyRunMap:
    """run_id -> job_id through run_index; writes buffer until flush."""

    def __init__(self, store: "SqliteLookoutStore"):
        self.store = store
        self.pending: dict[str, str | None] = {}  # None = delete

    def get(self, run_id, default=""):
        if run_id in self.pending:
            v = self.pending[run_id]
            return default if v is None else v
        cur = self.store._db.execute(
            "SELECT job_id FROM run_index WHERE run_id=?", (run_id,)
        ).fetchone()
        return cur[0] if cur else default

    def __setitem__(self, run_id, job_id):
        self.pending[run_id] = job_id

    def pop(self, run_id, default=None):
        self.pending[run_id] = None
        return default


class SqliteLookoutStore:
    """Drop-in persistent LookoutStore; see module docstring."""

    def __init__(self, log, path: str, error_rules=()):
        self.log = log
        self.error_rules = error_rules
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.executescript(_SCHEMA)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        # Separate read connection: WAL readers never block on the
        # ingester's write transactions, so UI queries don't queue behind
        # a busy sync loop (the reference gets this from Postgres MVCC).
        self._read_db = sqlite3.connect(path, check_same_thread=False)
        self._read_db.execute("PRAGMA query_only=1")
        # Match the scan path's case-SENSITIVE startsWith/contains.
        self._read_db.execute("PRAGMA case_sensitive_like=1")
        self._read_lock = threading.Lock()
        self._lock = threading.RLock()
        cur = self._db.execute(
            "SELECT value FROM meta WHERE key='cursor'"
        ).fetchone()
        self.cursor = int(cur[0]) if cur else 0
        self.cursor = max(self.cursor, log.start_offset)
        self.rows = _LazyRowMap(self)
        self.run_to_job = _LazyRunMap(self)

    # ---- ingestion (shared event semantics) ----

    # The single source of event->row semantics is the in-memory store.
    _apply_shared = LookoutStore._apply

    def _apply(self, seq, event):
        from .. import events as ev

        if isinstance(event, ev.CancelJobSet):
            # The shared path scans every row; here only the jobset's
            # active rows are faulted in and mutated.
            cur = self._db.execute(
                "SELECT job_id FROM job WHERE queue=? AND jobset=? AND "
                f"state IN ({','.join('?' * len(_ACTIVE))})",
                (seq.queue, seq.jobset, *_ACTIVE),
            )
            for (jid,) in cur.fetchall():
                row = self.rows.get(jid)
                if row is not None and row.state in _ACTIVE:
                    row.state = "cancelled"
                    row.cancelled = event.created
                    row.last_transition = event.created
            # Rows already cached (possibly not yet flushed) match too.
            for row in list(self.rows.cache.values()):
                if (
                    row.queue == seq.queue
                    and row.jobset == seq.jobset
                    and row.state in _ACTIVE
                ):
                    row.state = "cancelled"
                    row.cancelled = event.created
                    row.last_transition = event.created
            return
        self._apply_shared(seq, event)

    def sync(self, limit: int = 10_000) -> int:
        """Apply new log entries; one transaction per batch (rows + run
        index + cursor move together — crash-consistent resume)."""
        applied = 0
        while True:
            entries = self.log.read(self.cursor, limit)
            if not entries:
                return applied
            with self._lock:
                try:
                    self._prefetch(entries)
                    for entry in entries:
                        for event in entry.sequence.events:
                            self._apply(entry.sequence, event)
                    self.cursor = entries[-1].offset + 1
                    self._flush()
                except Exception:
                    # A mid-batch failure must not leave half-applied rows
                    # in the cache: the caller's retry would re-apply the
                    # same events on top and persist doubled state. Drop
                    # the batch's in-memory work; the cursor did not move.
                    self.rows.cache.clear()
                    self.rows.absent.clear()
                    self.run_to_job.pending.clear()
                    raise
            applied += len(entries)

    def _prefetch(self, entries):
        """Fault every job row a batch touches in chunked IN-queries
        instead of one SELECT per event — the difference between the sync
        loop holding the write path for milliseconds vs seconds."""
        cache = self.rows.cache
        want: list[str] = []
        seen: set[str] = set()
        for entry in entries:
            for event in entry.sequence.events:
                jid = getattr(event, "job_id", "") or getattr(
                    getattr(event, "job", None), "id", ""
                )
                if jid and jid not in cache and jid not in seen:
                    seen.add(jid)
                    want.append(jid)
        for i in range(0, len(want), 500):
            chunk = want[i : i + 500]
            cur = self._db.execute(
                f"SELECT {','.join(_COLS)} FROM job WHERE job_id IN "
                f"({','.join('?' * len(chunk))})",
                chunk,
            )
            found = set()
            for rec in cur.fetchall():
                cache[rec[0]] = _record_to_row(rec)
                found.add(rec[0])
            self.rows.absent.update(jid for jid in chunk if jid not in found)

    def _flush(self):
        cache = self.rows.cache
        if cache:
            self._db.executemany(
                f"INSERT OR REPLACE INTO job({','.join(_COLS)}) "
                f"VALUES ({','.join('?' * len(_COLS))})",
                [_row_to_record(r) for r in cache.values()],
            )
        pend = self.run_to_job.pending
        if pend:
            ins = [(rid, jid) for rid, jid in pend.items() if jid is not None]
            dels = [(rid,) for rid, jid in pend.items() if jid is None]
            if ins:
                self._db.executemany(
                    "INSERT OR REPLACE INTO run_index(run_id, job_id) "
                    "VALUES (?, ?)",
                    ins,
                )
            if dels:
                self._db.executemany(
                    "DELETE FROM run_index WHERE run_id=?", dels
                )
        self._db.execute(
            "INSERT OR REPLACE INTO meta(key, value) VALUES ('cursor', ?)",
            (str(self.cursor),),
        )
        self._db.commit()
        cache.clear()
        self.rows.absent.clear()
        pend.clear()

    @property
    def lag_events(self) -> int:
        return max(0, self.log.end_offset - self.cursor)

    def _load_row(self, job_id: str) -> LookoutRow | None:
        rec = self._db.execute(
            f"SELECT {','.join(_COLS)} FROM job WHERE job_id=?", (job_id,)
        ).fetchone()
        return _record_to_row(rec) if rec else None

    # ---- reads (QueryApi surface) ----

    def all_rows(self) -> list[LookoutRow]:
        with self._read_lock:
            cur = self._read_db.execute(f"SELECT {','.join(_COLS)} FROM job")
            return [_record_to_row(r) for r in cur.fetchall()]

    def get(self, job_id: str) -> LookoutRow | None:
        with self._read_lock:
            rec = self._read_db.execute(
                f"SELECT {','.join(_COLS)} FROM job WHERE job_id=?", (job_id,)
            ).fetchone()
            return _record_to_row(rec) if rec else None

    def materialize(self, rows, convert):
        # all_rows() returns detached copies — already consistent.
        return [convert(r) for r in rows]

    def get_run(self, run_id: str) -> LookoutRun | None:
        with self._read_lock:
            cur = self._read_db.execute(
                "SELECT job_id FROM run_index WHERE run_id=?", (run_id,)
            ).fetchone()
            jid = cur[0] if cur else ""
            rec = self._read_db.execute(
                f"SELECT {','.join(_COLS)} FROM job WHERE job_id=?", (jid,)
            ).fetchone() if jid else None
            row = _record_to_row(rec) if rec else None
            if row is None:
                return None
            for r in row.runs:
                if r.run_id == run_id:
                    return r
            return None

    def prune(self, older_than: float) -> int:
        """Retention pruner (internal/lookout/pruner): drop terminal rows
        whose last transition predates the window, plus their run index."""
        with self._lock:
            cur = self._db.execute(
                "SELECT job_id FROM job WHERE last_transition<? AND "
                f"state IN ({','.join('?' * len(_TERMINAL))})",
                (older_than, *_TERMINAL),
            )
            drop = [jid for (jid,) in cur.fetchall()]
            if drop:
                qs = ",".join("?" * len(drop))
                self._db.execute(
                    f"DELETE FROM run_index WHERE job_id IN ({qs})", drop
                )
                self._db.execute(
                    f"DELETE FROM job WHERE job_id IN ({qs})", drop
                )
                self._db.commit()
            return len(drop)

    # ---- SQL pushdown (QueryApi.get_jobs fast path) ----

    # Fields that are plain job-table columns; anything else (annotation
    # filters, run-level fields) falls back to the generic scan.
    _SQL_FIELDS = frozenset(
        "job_id queue jobset state priority priority_class submitted "
        "last_transition cancelled error error_category".split()
    )
    # startsWith/contains push down only for text columns: the scan path
    # requires isinstance(str), while SQL LIKE would coerce numerics.
    _TEXT_FIELDS = frozenset(
        "job_id queue jobset state priority_class error "
        "error_category".split()
    )

    @staticmethod
    def _like_escape(s: str) -> str:
        return s.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")

    def _filters_to_sql(self, filters, allowed=None):
        """JobFilter list -> (conds, params), or None when a predicate is
        not SQL-expressible (querybuilder.go's operator translation).
        `allowed` optionally restricts the match kinds (group pushdown
        supports the equality family only)."""
        conds: list[str] = []
        params: list = []
        for f in filters:
            if f.is_annotation or f.field not in self._SQL_FIELDS:
                return None
            if allowed is not None and f.match not in allowed:
                return None
            col = f.field
            if f.match == "exact":
                conds.append(f"{col}=?")
                params.append(f.value)
            elif f.match == "anyOf":
                vals = list(f.value or ())
                if not vals:
                    conds.append("0")
                else:
                    conds.append(f"{col} IN ({','.join('?' * len(vals))})")
                    params.extend(vals)
            elif f.match == "startsWith":
                if col not in self._TEXT_FIELDS:
                    return None
                conds.append(f"{col} LIKE ? ESCAPE '\\'")
                params.append(self._like_escape(str(f.value)) + "%")
            elif f.match == "contains":
                if col not in self._TEXT_FIELDS:
                    return None
                conds.append(f"{col} LIKE ? ESCAPE '\\'")
                params.append("%" + self._like_escape(str(f.value)) + "%")
            elif f.match == "greaterThan":
                conds.append(f"{col}>?")
                params.append(f.value)
            elif f.match == "lessThan":
                conds.append(f"{col}<?")
                params.append(f.value)
            elif f.match == "greaterThanOrEqualTo":
                conds.append(f"{col}>=?")
                params.append(f.value)
            elif f.match == "lessThanOrEqualTo":
                conds.append(f"{col}<=?")
                params.append(f.value)
            elif f.match == "exists":
                conds.append(f"({col} IS NOT NULL AND {col}!='')")
            else:
                return None
        return conds, params

    def query_rows(self, filters, order, skip: int, take: int):
        """Filter/sort/page in SQL (querybuilder.go's role). Returns
        (page LookoutRows, total) or None when a predicate isn't
        SQL-expressible — the caller then uses the all_rows() scan.
        Ties break on job_id for determinism."""
        translated = self._filters_to_sql(filters)
        if translated is None:
            return None
        conds, params = translated
        if order.field not in self._SQL_FIELDS:
            return None
        where = (" WHERE " + " AND ".join(conds)) if conds else ""
        direction = "DESC" if order.direction == "desc" else "ASC"
        with self._read_lock:
            total = self._read_db.execute(
                f"SELECT COUNT(*) FROM job{where}", params
            ).fetchone()[0]
            # job_id follows the primary direction (matching the scan
            # path's composite key), so a single (field, job_id) index
            # serves both directions as a pure (reverse) scan — no temp
            # b-tree sort on the UI's hot path.
            cur = self._read_db.execute(
                f"SELECT {','.join(_COLS)} FROM job{where} "
                f"ORDER BY {order.field} {direction}, job_id {direction} "
                "LIMIT ? OFFSET ?",
                (*params, take, skip),
            )
            return [_record_to_row(r) for r in cur.fetchall()], total

    def group_rows(self, group_by: str, filters, agg_specs):
        """GROUP BY pushdown for QueryApi.group_jobs: returns the groups
        dict in the scan path's intermediate format (averages as
        {'sum','n'} buckets), or None when the shape isn't SQL-expressible
        (annotation group-bys, computed columns like runtime)."""
        if group_by not in self._SQL_FIELDS:
            return None
        translated = self._filters_to_sql(filters, allowed=("exact", "anyOf"))
        if translated is None:
            return None
        conds, params = translated
        sel = [group_by, "COUNT(*)"]
        post: list = []  # (agg_name, kind) aligned with extra select cols
        counts_aggs: list = []  # (agg_name, column) via secondary queries
        for agg, col, typ in agg_specs:
            if col is not None and col in self._SQL_FIELDS:
                if typ == "min":
                    sel.append(f"MIN({col})")
                    post.append((agg, "plain"))
                elif typ == "max":
                    sel.append(f"MAX({col})")
                    post.append((agg, "plain"))
                elif typ == "average":
                    sel.append(f"SUM(COALESCE({col},0))")
                    post.append((agg, "avg"))
                else:
                    return None
            elif agg == "submitted_min":
                sel.append("MIN(submitted)")
                post.append((agg, "plain"))
            elif agg == "submitted_max":
                sel.append("MAX(submitted)")
                post.append((agg, "plain"))
            elif agg == "last_transition_max":
                sel.append("MAX(last_transition)")
                post.append((agg, "plain"))
            elif agg == "state_counts":
                counts_aggs.append((agg, "state"))
            elif agg == "error_category_counts":
                counts_aggs.append((agg, "error_category"))
            else:
                return None
        where = (" WHERE " + " AND ".join(conds)) if conds else ""
        with self._read_lock:
            cur = self._read_db.execute(
                f"SELECT {','.join(sel)} FROM job{where} GROUP BY {group_by}",
                params,
            )
            groups = {}
            for rec in cur.fetchall():
                g = {"name": rec[0], "count": rec[1], "aggregates": {}}
                for i, (agg, kind) in enumerate(post):
                    if kind == "avg":
                        g["aggregates"][agg] = {
                            "sum": float(rec[2 + i] or 0.0),
                            "n": rec[1],
                        }
                    else:
                        g["aggregates"][agg] = rec[2 + i]
                groups[rec[0]] = g
            for agg, col in counts_aggs:
                cur = self._read_db.execute(
                    f"SELECT {group_by}, {col}, COUNT(*) FROM job{where} "
                    f"GROUP BY {group_by}, {col}",
                    params,
                )
                for gval, cval, n in cur.fetchall():
                    g = groups.get(gval)
                    if g is None:
                        continue
                    if agg == "error_category_counts" and not cval:
                        continue  # the scan path skips empty categories
                    g["aggregates"].setdefault(agg, {})[cval] = n
        return groups

    def count(self) -> int:
        with self._read_lock:
            return self._read_db.execute(
                "SELECT COUNT(*) FROM job"
            ).fetchone()[0]

    def checkpoint_state(self):
        """The database file IS the checkpoint; nothing to serialize."""
        with self._lock:
            return self.cursor, {"rows": {}, "run_to_job": {}}

    def close(self):
        with self._lock:
            self._db.commit()
            self._db.close()
        with self._read_lock:
            self._read_db.close()
