"""Prometheus metrics for the scheduler and control plane.

Covers the reference's headline scheduler metrics
(/root/reference/internal/scheduler/metrics/{metrics,cycle_metrics}.go):
cycle time, per-queue/pool fair share vs actual share, demand, scheduled and
preempted counts, and job state-transition counters. Exposed via
prometheus_client's text endpoint.
"""

from __future__ import annotations

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROMETHEUS = True
except Exception:  # pragma: no cover
    HAVE_PROMETHEUS = False


class SchedulerMetrics:
    def __init__(self, registry=None):
        if not HAVE_PROMETHEUS:
            self.registry = None
            return
        self.registry = registry or CollectorRegistry()
        r = self.registry
        self.cycle_time = Histogram(
            "scheduler_cycle_seconds",
            "Wall-clock time of one scheduling cycle",
            registry=r,
        )
        self.solve_time = Histogram(
            "scheduler_solve_seconds",
            "Device solve time within a cycle",
            ["pool"],
            registry=r,
        )
        self.fair_share = Gauge(
            "scheduler_queue_fair_share",
            "Demand-capped adjusted fair share",
            ["pool", "queue"],
            registry=r,
        )
        self.actual_share = Gauge(
            "scheduler_queue_actual_share",
            "Actual share of pool resources",
            ["pool", "queue"],
            registry=r,
        )
        self.skipped_executors = Gauge(
            "scheduler_skipped_executors",
            "Executors excluded from the current round (cordoned or lagging)",
            registry=r,
        )
        self.scheduled_jobs = Counter(
            "scheduler_jobs_scheduled_total",
            "Jobs scheduled",
            ["pool", "queue"],
            registry=r,
        )
        self.preempted_jobs = Counter(
            "scheduler_jobs_preempted_total",
            "Jobs preempted",
            ["pool", "queue"],
            registry=r,
        )
        self.considered_jobs = Gauge(
            "scheduler_jobs_considered",
            "Jobs considered in the last round",
            ["pool"],
            registry=r,
        )
        self.job_state_transitions = Counter(
            "scheduler_job_state_transitions_total",
            "Job state transitions observed",
            ["state"],
            registry=r,
        )
        self.event_log_offset = Gauge(
            "event_log_end_offset", "End offset of the event log", registry=r
        )

    def render(self) -> bytes:
        if not HAVE_PROMETHEUS:
            return b""
        return generate_latest(self.registry)


def serve_metrics(metrics: SchedulerMetrics, port: int):
    """Tiny HTTP endpoint serving /metrics (common.ServeMetrics)."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = metrics.render()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
