"""Prometheus metrics for the scheduler and control plane.

Covers the reference's headline scheduler metrics
(/root/reference/internal/scheduler/metrics/{metrics,cycle_metrics}.go):
cycle time, per-queue/pool fair share vs actual share, demand, scheduled and
preempted counts, and job state-transition counters. Exposed via
prometheus_client's text endpoint.
"""

from __future__ import annotations

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROMETHEUS = True
except Exception:  # pragma: no cover
    HAVE_PROMETHEUS = False


class SchedulerMetrics:
    def __init__(self, registry=None):
        if not HAVE_PROMETHEUS:
            self.registry = None
            return
        self.registry = registry or CollectorRegistry()
        r = self.registry
        self.cycle_time = Histogram(
            "scheduler_cycle_seconds",
            "Wall-clock time of one scheduling cycle",
            registry=r,
        )
        self.solve_time = Histogram(
            "scheduler_solve_seconds",
            "Device solve time within a cycle",
            ["pool"],
            registry=r,
        )
        self.fair_share = Gauge(
            "scheduler_queue_fair_share",
            "Demand-capped adjusted fair share",
            ["pool", "queue"],
            registry=r,
        )
        self.actual_share = Gauge(
            "scheduler_queue_actual_share",
            "Actual share of pool resources",
            ["pool", "queue"],
            registry=r,
        )
        self.idealised_value = Gauge(
            "scheduler_queue_idealised_value",
            "Theoretical max value on a single mega node "
            "(idealised_value.go; market pools)",
            ["pool", "queue"],
            registry=r,
        )
        self.realised_value = Gauge(
            "scheduler_queue_realised_value",
            "Value actually placed this round (market pools)",
            ["pool", "queue"],
            registry=r,
        )
        self.skipped_executors = Gauge(
            "scheduler_skipped_executors",
            "Executors excluded from the current round (cordoned or lagging)",
            registry=r,
        )
        self.scheduled_jobs = Counter(
            "scheduler_jobs_scheduled_total",
            "Jobs scheduled",
            ["pool", "queue"],
            registry=r,
        )
        self.preempted_jobs = Counter(
            "scheduler_jobs_preempted_total",
            "Jobs preempted",
            ["pool", "queue"],
            registry=r,
        )
        self.considered_jobs = Gauge(
            "scheduler_jobs_considered",
            "Jobs considered in the last round",
            ["pool"],
            registry=r,
        )
        self.job_state_transitions = Counter(
            "scheduler_job_state_transitions_total",
            "Job state transitions observed",
            ["state"],
            registry=r,
        )
        self.event_log_offset = Gauge(
            "event_log_end_offset", "End offset of the event log", registry=r
        )
        # ---- depth mirroring metrics/cycle_metrics.go + state_metrics.go ----
        # Preemptions by mechanism (cycle_metrics.go:531 preemption types):
        # round (fairness/urgency in the solve), oversubscription repair,
        # reconciliation, optimiser.
        self.preempted_by_type = Counter(
            "scheduler_jobs_preempted_by_type_total",
            "Jobs preempted, by preemption mechanism",
            ["pool", "type"],
            registry=r,
        )
        # Per-queue state-transition counters with queue granularity.
        self.queue_state_transitions = Counter(
            "scheduler_queue_job_state_transitions_total",
            "Job state transitions by queue",
            ["queue", "state"],
            registry=r,
        )
        # Time-in-state at transition (state_metrics.go checkpoint
        # intervals): queued->leased, leased->running, running->done.
        self.state_seconds = Histogram(
            "scheduler_job_state_seconds",
            "Seconds spent in the previous state at each transition",
            ["transition"],
            buckets=(0.1, 1, 5, 15, 60, 300, 1800, 7200, 86400),
            registry=r,
        )
        self.queue_demand = Gauge(
            "scheduler_queue_demand",
            "Queue demand as dominant-share cost",
            ["pool", "queue"],
            registry=r,
        )
        # Ingestion lag (common/ingest/metrics + topic_delay_monitor.go):
        # events between the log end and the ingester cursor.
        self.ingester_lag = Gauge(
            "ingester_lag_events",
            "Events the scheduler ingester has not applied yet",
            registry=r,
        )
        self.snapshot_build_seconds = Histogram(
            "scheduler_snapshot_build_seconds",
            "Host-side snapshot + device-prep time per pool round",
            ["pool"],
            registry=r,
        )
        # Device-resident round state (snapshot/residency.py): which
        # snapshot strategy each pool round actually used, so residency
        # engagement (and per-pool demotions back to rebuild) is
        # observable; and the live drift guard behind the
        # resident_drift divergence kind.
        self.snapshot_mode_total = Counter(
            "scheduler_snapshot_mode_total",
            "Pool rounds by the snapshot strategy actually used",
            ["pool", "mode"],
            registry=r,
        )
        self.resident_drift = Counter(
            "scheduler_resident_drift_total",
            "Device-resident round buffers found drifted from the host "
            "mirror (the resident state was reset and re-uploads next "
            "cycle; the already-committed round was validated against "
            "the mirror by the admission firewall)",
            ["pool"],
            registry=r,
        )
        self.solve_loops = Gauge(
            "scheduler_solve_loops",
            "while-loop iterations of the last device solve",
            ["pool"],
            registry=r,
        )
        # Market mode: per-shape indicative gang price
        # (cycle_metrics.go:681 indicativePrice gauges).
        self.indicative_gang_price = Gauge(
            "scheduler_indicative_gang_price",
            "Minimum bid at which the configured gang shape would schedule",
            ["pool", "shape"],
            registry=r,
        )
        self.indicative_gang_schedulable = Gauge(
            "scheduler_indicative_gang_schedulable",
            "1 if the configured gang shape is currently schedulable",
            ["pool", "shape"],
            registry=r,
        )
        # Round-deadline guardrail (maxSchedulingDuration): rounds cut by
        # the budget, and the consecutive-truncation streak that trips
        # per-pool backpressure (backpressure.RoundDeadlinePressure).
        self.truncated_rounds = Counter(
            "scheduler_rounds_truncated_total",
            "Scheduling rounds truncated by maxSchedulingDuration",
            ["pool"],
            registry=r,
        )
        self.round_truncation_streak = Gauge(
            "scheduler_round_truncation_streak",
            "Consecutive truncated rounds per pool",
            ["pool"],
            registry=r,
        )
        # ---- self-healing solve path (solver/validate.py admission
        # firewall + solver/failover.py backend ladder) ----
        self.round_rejected = Counter(
            "scheduler_round_rejected_total",
            "Scheduling rounds rejected by the admission firewall, by "
            "first violated invariant (nothing committed; a postmortem "
            ".atrace bundle was captured for offline replay)",
            ["pool", "invariant"],
            registry=r,
        )
        self.solver_failover = Counter(
            "scheduler_solver_failover_total",
            "Rounds retried down the solver backend failover ladder",
            ["from", "to", "cause"],
            registry=r,
        )
        self.solver_rung_state = Gauge(
            "scheduler_solver_rung_state",
            "Failover-ladder circuit-breaker state per backend rung "
            "(0 = closed, 1 = half-open, 2 = open)",
            ["rung"],
            registry=r,
        )
        self.executor_heartbeat_age = Gauge(
            "scheduler_executor_heartbeat_age_seconds",
            "Seconds since each executor's last heartbeat",
            ["executor"],
            registry=r,
        )
        # ---- partition / lease-fencing surface (services/netchaos.py
        # chaos + the split-brain protocol in docs/architecture.md) ----
        self.fence_rejections = Counter(
            "scheduler_fence_rejections_total",
            "Lease/report RPCs rejected FAILED_PRECONDITION for carrying "
            "a stale fencing token",
            ["executor", "method"],
            registry=r,
        )
        self.executor_fence = Gauge(
            "scheduler_executor_fence",
            "Current monotonic fencing token per executor (bumped when "
            "its runs are reassigned after a partition)",
            ["executor"],
            registry=r,
        )
        self.executor_reconnects = Counter(
            "scheduler_executor_reconnects_total",
            "Heartbeats that healed a disconnected executor",
            ["executor"],
            registry=r,
        )
        self.reconnect_latency = Histogram(
            "scheduler_executor_reconnect_seconds",
            "Outage length: executor drop (heartbeat expiry) to the "
            "first heartbeat after the heal",
            buckets=(1, 5, 15, 60, 300, 900, 3600, 14400),
            registry=r,
        )
        # ---- two-level mesh surface (parallel/multihost.py): topology,
        # trace-time collective accounting of the compiled sharded round
        # program, and the per-host sharded-solve wall clock — the gauges
        # the DCN cost model in docs/architecture.md regresses against.
        self.solve_mesh_extent = Gauge(
            "scheduler_solve_mesh_extent",
            "Sharded-solve mesh extent by axis (hosts / chips)",
            ["axis"],
            registry=r,
        )
        self.solve_collective_sites = Gauge(
            "scheduler_solve_collective_sites",
            "Cross-shard collective call sites traced into the compiled "
            "round program, by kind (selects / fills / point_ops)",
            ["kind"],
            registry=r,
        )
        self.solve_collective_bytes = Gauge(
            "scheduler_solve_collective_bytes",
            "Bytes one shard receives per execution of all traced "
            "collective sites, by fabric level (ici / dcn)",
            ["level"],
            registry=r,
        )
        self.solve_dcn_scalars_per_select = Gauge(
            "scheduler_solve_dcn_scalars_per_select",
            "Cross-host scalars per candidate selection: one winner "
            "tuple per host (O(hosts x keys), chip count cancels)",
            registry=r,
        )
        self.shard_solve_time = Histogram(
            "scheduler_shard_solve_seconds",
            "Per-host wall clock of the sharded round solve",
            ["pool"],
            registry=r,
        )
        # ---- hot-window solve profile (solver/hotwindow.py): wall clock
        # per solve segment and the pass-1 loop mix, from the host-driven
        # kernel driver. The numbers future perf PRs regress against —
        # "the round is solve-bound" stops being one opaque histogram.
        self.solve_segment_time = Histogram(
            "scheduler_solve_segment_seconds",
            "Device solve wall clock by segment (setup / pass1 / "
            "gather / finish) within a round",
            ["pool", "segment"],
            buckets=(0.001, 0.01, 0.05, 0.2, 1, 5, 20, 60),
            registry=r,
        )
        self.solve_loops_by_kind = Gauge(
            "scheduler_solve_loops_by_kind",
            "Pass-1 while-loop iterations of the last solve by kind "
            "(gang = serial attempts, fill, merged_fill)",
            ["pool", "kind"],
            registry=r,
        )
        self.solve_rewindows = Gauge(
            "scheduler_solve_rewindows",
            "Hot-window re-gathers during the last solve's pass 1",
            ["pool"],
            registry=r,
        )
        self.solve_window_slots = Gauge(
            "scheduler_solve_window_slots",
            "Per-queue hot-window size of the last solve (0 = "
            "compaction disengaged)",
            ["pool"],
            registry=r,
        )
        # ---- flight recorder (armada_tpu/trace): capture volume from
        # the attached TraceRecorder, and the divergence counter the
        # replayer bumps when a re-solved round drifts from the
        # recorded decision stream (kinds: placement / loop_stream /
        # profile_regression).
        self.trace_rounds_recorded = Counter(
            "scheduler_trace_rounds_recorded",
            "Scheduling rounds appended to the flight-recorder bundle",
            ["pool"],
            registry=r,
        )
        self.trace_bytes_written = Counter(
            "scheduler_trace_bytes_written",
            "Bytes appended to the flight-recorder .atrace bundle",
            registry=r,
        )
        self.trace_replay_divergences = Counter(
            "scheduler_trace_replay_divergences",
            "Replayed-round divergences from the recorded decision "
            "stream, by classification",
            ["kind"],
            registry=r,
        )
        # ---- job-journey surface (services/job_timeline.py): how long
        # jobs wait and WHY — per-decision attribution instead of only
        # aggregate shares.
        self.job_rounds_to_schedule = Histogram(
            "scheduler_job_rounds_to_schedule",
            "Scheduling rounds from submission through lease, per leased "
            "job (1 = leased in its first round)",
            buckets=(1, 2, 3, 5, 8, 13, 21, 34, 55, 89, 144, 233),
            registry=r,
        )
        self.job_queue_wait = Histogram(
            "scheduler_job_queue_wait_seconds",
            "Submission-to-lease wall clock per leased job, by queue",
            ["queue"],
            buckets=(0.1, 1, 5, 15, 60, 300, 1800, 7200, 86400),
            registry=r,
        )
        self.unschedulable_reason = Counter(
            "scheduler_unschedulable_reason_total",
            "Per-round per-job unschedulable verdicts, by reason",
            ["reason"],
            registry=r,
        )
        # ---- solver autopilot (armada_tpu/autotune): the perf-only
        # parameter vector each pool currently runs with, and every
        # adopted online change — so an operator can see exactly when
        # and why the closed loop moved a knob.
        self.autotune_window_slots = Gauge(
            "scheduler_autotune_window_slots",
            "Hot-window size the autotune controller currently applies "
            "(per-queue slots; 0 = compaction disabled)",
            ["pool"],
            registry=r,
        )
        self.autotune_chunk_loops = Gauge(
            "scheduler_autotune_chunk_loops",
            "Budgeted pass-1 starting chunk stride the autotune "
            "controller currently applies",
            ["pool"],
            registry=r,
        )
        self.autotune_adjustments = Counter(
            "scheduler_autotune_adjustments_total",
            "Online parameter changes adopted by the autotune "
            "controller, by direction",
            ["pool", "direction"],
            registry=r,
        )
        self.autotune_store_entries = Gauge(
            "scheduler_autotune_store_entries",
            "Entries in the persisted tuning store (offline profiles + "
            "online adoptions)",
            registry=r,
        )
        # ---- what-if planner (armada_tpu/whatif): plan volume/latency
        # on the bounded off-round-thread worker, the pending backlog
        # the backpressure cap guards, and drain progress through the
        # staged cordon -> voluntary completion -> preempt-requeue path.
        self.whatif_plans = Counter(
            "whatif_plans_total",
            "What-if plans completed, by kind (whatif / drain / parity)",
            ["kind"],
            registry=r,
        )
        self.whatif_plan_seconds = Histogram(
            "whatif_plan_seconds",
            "Wall clock of one what-if plan (fork + mutate + bounded "
            "rollout + diff), by kind",
            ["kind"],
            buckets=(0.01, 0.05, 0.2, 1, 5, 20, 60, 300),
            registry=r,
        )
        self.whatif_queue_depth = Gauge(
            "whatif_queue_depth",
            "What-if plans pending on the bounded planner worker",
            registry=r,
        )
        self.drain_jobs_preempted = Counter(
            "drain_jobs_preempted_total",
            "Jobs preempt-requeued by a drain's deadline (gang-aware)",
            ["executor"],
            registry=r,
        )
        self.drain_jobs_completed = Counter(
            "drain_jobs_completed_total",
            "Drained-executor jobs that completed voluntarily before "
            "the drain deadline",
            ["executor"],
            registry=r,
        )
        self.anti_entropy_resolutions = Counter(
            "scheduler_anti_entropy_resolutions_total",
            "Run resolutions produced by post-partition ExecutorSync "
            "(zombie / duplicate / orphaned / kept)",
            ["resolution"],
            registry=r,
        )
        # ---- front door (armada_tpu/frontdoor): the sharded-ingest +
        # admission surface. Shard lag is the acked-but-undelivered
        # backlog per ingest shard (the soak's SLO input); admitted/shed
        # attribute intake decisions to TENANTS so an operator can find
        # the hot queue during an overload (docs/operations.md runbook).
        self.frontdoor_shard_lag = Gauge(
            "frontdoor_shard_lag_events",
            "Acked submissions not yet delivered into the main event "
            "log, per ingest shard",
            ["shard"],
            registry=r,
        )
        self.frontdoor_admitted = Counter(
            "frontdoor_admitted_total",
            "Jobs admitted through the front door, by tenant (queue)",
            ["tenant"],
            registry=r,
        )
        self.frontdoor_shed = Counter(
            "frontdoor_shed_total",
            "Jobs shed by admission control, by tenant and reason class "
            "(tenantRate / globalRate / overload)",
            ["tenant", "reason"],
            registry=r,
        )
        self.frontdoor_submit_time = Histogram(
            "frontdoor_submit_seconds",
            "Submit handler wall clock through admission + durable "
            "shard-WAL ack, by outcome (ok / shed / expired / error)",
            ["outcome"],
            buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1, 5),
            registry=r,
        )
        self.frontdoor_deadline_drops = Counter(
            "frontdoor_deadline_drops_total",
            "Submissions dropped because the propagated client deadline "
            "expired, by stage (gate = before processing, enqueue = "
            "before the WAL append; acked work is never dropped)",
            ["stage"],
            registry=r,
        )
        self.frontdoor_delivered = Counter(
            "frontdoor_delivered_total",
            "Shard-ingester deliveries into the main log, by shard and "
            "outcome (published / duplicate = suppressed redelivery "
            "after a crash)",
            ["shard", "outcome"],
            registry=r,
        )
        # ---- round observatory (armada_tpu/observe): the host↔device
        # transfer ledger and compile telemetry. These are the numbers
        # the ROADMAP-1 device-resident-round refactor must move: bytes
        # uploaded per round (what residency would amortize away),
        # donated-buffer traffic (what the donation machinery already
        # avoids), and warm-cycle XLA compiles (which must be zero).
        self.round_transfer_bytes = Gauge(
            "scheduler_round_transfer_bytes",
            "Bytes the last solved round moved across the host↔device "
            "boundary, by direction (up = host→device uploads, down = "
            "result materialization, donated = device buffers updated "
            "in place via donation — avoided traffic)",
            ["pool", "direction"],
            registry=r,
        )
        self.round_transfer_arrays = Gauge(
            "scheduler_round_transfer_arrays",
            "Array/buffer count behind scheduler_round_transfer_bytes "
            "for the last solved round",
            ["pool", "direction"],
            registry=r,
        )
        self.transfer_bytes_total = Counter(
            "scheduler_transfer_bytes_total",
            "Cumulative host↔device bytes booked by the round transfer "
            "ledger, by direction",
            ["direction"],
            registry=r,
        )
        self.xla_compiles = Counter(
            "scheduler_xla_compiles_total",
            "XLA backend compiles observed during scheduling rounds "
            "(jax.monitoring; a warm steady state compiles nothing)",
            registry=r,
        )
        self.xla_retraces = Counter(
            "scheduler_xla_retraces_total",
            "Jitted-entrypoint tracing events observed during "
            "scheduling rounds (every retrace risks a compile)",
            registry=r,
        )
        self.xla_compile_seconds = Counter(
            "scheduler_xla_compile_seconds",
            "Cumulative XLA backend-compile wall clock spent inside "
            "scheduling rounds",
            registry=r,
        )
        self.xla_cache_events = Counter(
            "scheduler_xla_cache_events_total",
            "Persistent compile-cache lookups during scheduling rounds, "
            "by outcome (hit / miss)",
            ["outcome"],
            registry=r,
        )
        # ---- SLO layer (services/slo.py): declared objectives over
        # round latency / queue wait / front-door submit latency, with
        # multi-window burn rates — what the soaks and tools/slo_gate.py
        # gate on, and what an operator pages on.
        self.slo_events = Counter(
            "scheduler_slo_events_total",
            "SLO-signal observations, by SLO name and verdict (good = "
            "within threshold, bad = breached it)",
            ["slo", "verdict"],
            registry=r,
        )
        self.slo_burn_rate = Gauge(
            "scheduler_slo_burn_rate",
            "Error-budget burn rate per SLO and window (1.0 = burning "
            "exactly the budget; the multiwindow alert fires when fast "
            "AND slow windows both exceed their thresholds)",
            ["slo", "window"],
            registry=r,
        )
        self.slo_compliance = Gauge(
            "scheduler_slo_compliance",
            "Good-event fraction per SLO over the tracker's full "
            "retention window (compare against the objective)",
            ["slo"],
            registry=r,
        )
        # ---- fairness observatory (armada_tpu/observe/fairness.py):
        # the round OUTCOME surface. The full fair-share triple per
        # queue — demand-capped (scheduler_queue_fair_share above),
        # uncapped entitlement, and demand share — lets dashboards
        # distinguish "capped by demand" from "starved"; regret and the
        # starved-rounds streak are the starvation-alert inputs; the
        # attribution counter answers "who is preempting whom".
        self.fair_share_uncapped = Gauge(
            "scheduler_queue_fair_share_uncapped",
            "Uncapped adjusted fair share (the entitlement the queue "
            "would hold were its demand unbounded; drf.py water-filling "
            "triple)",
            ["pool", "queue"],
            registry=r,
        )
        self.queue_demand_share = Gauge(
            "scheduler_queue_demand_share",
            "Queue demand as DRF dominant-share cost of the round's "
            "full (running + queued) demand",
            ["pool", "queue"],
            registry=r,
        )
        self.fairness_regret = Gauge(
            "scheduler_fairness_regret",
            "Per-queue fairness error: entitlement (demand-capped "
            "adjusted fair share) minus delivered dominant share, "
            "floored at zero",
            ["pool", "queue"],
            registry=r,
        )
        self.fairness_jain = Gauge(
            "scheduler_fairness_jain",
            "Jain fairness index of the pool's delivered-per-weight "
            "shares over competing queues (1.0 = perfectly "
            "proportional)",
            ["pool"],
            registry=r,
        )
        self.fairness_starved_rounds = Gauge(
            "scheduler_fairness_starved_rounds",
            "Consecutive rounds the queue has been starved (below its "
            "entitlement with unsatisfied demand); 0 = healthy",
            ["pool", "queue"],
            registry=r,
        )
        self.fairness_starvation_alerts = Counter(
            "scheduler_fairness_starvation_alerts_total",
            "Multiwindow starvation alerts fired (K consecutive "
            "starved rounds AND starved in at least half of a 4xK "
            "trailing window)",
            ["pool", "queue"],
            registry=r,
        )
        self.fairness_policy_info = Gauge(
            "scheduler_fairness_policy_info",
            "Active fairness policy per pool (info-style gauge: the "
            "series labelled with the live policy reads 1, stale policy "
            "series read 0 after a flip)",
            ["pool", "policy"],
            registry=r,
        )
        self.solve_kernel_info = Gauge(
            "scheduler_solve_kernel_info",
            "Active solve kernel path per pool (info-style gauge: the "
            "series labelled with the path the last committed round "
            "actually ran — lax/blocked/pallas/native — reads 1; stale "
            "path series read 0 after a failover demotion or flip)",
            ["pool", "path"],
            registry=r,
        )
        self.preemption_attributed = Counter(
            "scheduler_preemption_attributed_total",
            "Round preemptions attributed to an aggressor queue, by "
            "mechanism (fairness = DRF rebalance, urgency = higher "
            "scheduled priority)",
            ["aggressor_queue", "mechanism"],
            registry=r,
        )

    def render(self) -> bytes:
        if not HAVE_PROMETHEUS:
            return b""
        return generate_latest(self.registry)


def serve_metrics(metrics: SchedulerMetrics, port: int):
    """Tiny HTTP endpoint serving /metrics (common.ServeMetrics).

    Returns (server, bound_port): port 0 binds an ephemeral port (tests
    stop hard-coding ports and racing each other for them), the same
    contract as health.serve_health."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = metrics.render()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]
