"""Deterministic fault injection (chaos) for the control plane.

The reference tests failure behavior ad hoc (killed executors, Pulsar
outages, leader churn in integration environments); here fault injection is
a first-class, SEEDED artifact so failure behavior is reproducible and
assertable. A `FaultPlan` is a declarative schedule of faults on the same
clock its components run on (virtual time in the simulator, wall clock in
live agents); the same seed always yields the same plan, and every
injection decision is a pure function of (plan state, query), so two runs
of one seed produce identical histories — the property the chaos soak
(tools/chaos_soak.py) asserts.

Fault kinds:

  executor_crash   the executor loses all local pod state and stops
                   reporting for the window; on recovery it reports its
                   leased runs as lost (missing-pod reconciliation)
  executor_hang    the executor stops reporting but keeps state
  lease_slow       lease exchanges are delayed (`param` seconds; the
                   simulator models this by deferring lease pickup)
  lease_timeout    lease RPCs fail with a timeout
  torn_log_write   an event-log append "crashes" mid-record, leaving a
                   torn tail for recovery to truncate
  leader_flap      leadership is lost for the window

Network fault kinds — consumed by the TCP chaos proxy
(services/netchaos.py) between real processes, and by the simulator /
FakeExecutor as virtual-clock partitions of the lease wire:

  network_partition  the wire is severed: live connections are killed and
                     new ones refused for the window (both directions)
  network_blackhole  bytes are silently swallowed; connections stay open
                     so callers hang until their own deadline fires
  network_delay      each forwarded chunk is delayed by `param` seconds
  network_throttle   forwarding is rate-limited (`param` scales the
                     byte rate; see netchaos.THROTTLE_BYTES_PER_SEC)
  network_rst        connections are reset (RST, not FIN) mid-stream

Alongside the plan live the degradation primitives injected faults are
met with: seeded exponential backoff with jitter (agent retry loop) and a
per-executor circuit breaker (the server's lease path), so a faulty
executor degrades its own lease flow instead of wedging a cycle.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass

NETWORK_FAULT_KINDS = (
    "network_partition",
    "network_blackhole",
    "network_delay",
    "network_throttle",
    "network_rst",
)

# Solver faults — injected at the kernel seam by SolverChaos below, the
# failure family the self-healing solve path (round admission firewall +
# backend failover ladder, solver/validate.py + solver/failover.py)
# exists to contain. Targets are ladder-rung labels ("LOCAL", "oracle",
# "mesh:2x4", "hotwindow:64"); "*" poisons every rung:
#
#   solver_raise            the solve raises mid-round (XLA runtime
#                           error / device lost / OOM stand-in)
#   solver_hang             the solve hangs past its budget (surfaced as
#                           SolverHangError — the watchdog's verdict)
#   solver_nan_poison       chosen output arrays are corrupted with NaN
#   solver_wrong_placement  decisions are perturbed (à la the replayer's
#                           tiebreak perturbation) into invalid bindings
SOLVER_FAULT_KINDS = (
    "solver_raise",
    "solver_hang",
    "solver_nan_poison",
    "solver_wrong_placement",
)

FAULT_KINDS = (
    "executor_crash",
    "executor_hang",
    "lease_slow",
    "lease_timeout",
    "torn_log_write",
    "leader_flap",
) + NETWORK_FAULT_KINDS + SOLVER_FAULT_KINDS

# Process-lifecycle kinds only: FaultPlan.generate defaults to these so
# pre-existing seeded soaks keep their schedules; network and solver
# kinds are opted into explicitly (tools/chaos_soak.py partition and
# solver-fault plans, netchaos tests).
PROCESS_FAULT_KINDS = tuple(
    k
    for k in FAULT_KINDS
    if k not in NETWORK_FAULT_KINDS + SOLVER_FAULT_KINDS
)


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: a window [start, start+duration) on a target
    ("*" matches any). `count` bounds point-fault firings inside the
    window (-1 = unlimited); `param` is kind-specific (delay seconds for
    lease_slow, torn-byte fraction for torn_log_write)."""

    kind: str
    target: str = "*"
    start: float = 0.0
    duration: float = float("inf")
    count: int = -1
    param: float = 0.0

    def matches(self, kind: str, target: str, now: float) -> bool:
        return (
            self.kind == kind
            and (self.target == "*" or self.target == target)
            and self.start <= now < self.start + self.duration
        )


class FaultPlan:
    """A seeded, declarative schedule of faults.

    Window queries (`active`) are pure; point-fault queries (`fire`)
    consume from the spec's count — still deterministic for a fixed
    sequence of queries, which a seeded run guarantees."""

    def __init__(self, faults=(), seed: int = 0):
        self.faults = tuple(faults)
        self.seed = seed
        for f in self.faults:
            if f.kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {f.kind!r}")
        self._fired = [0] * len(self.faults)
        self._observed: set[int] = set()

    def active(self, kind: str, target: str, now: float) -> FaultSpec | None:
        """The first matching window fault, ignoring counts."""
        for i, f in enumerate(self.faults):
            if f.matches(kind, target, now):
                self._observed.add(i)
                return f
        return None

    def fire(self, kind: str, target: str, now: float) -> FaultSpec | None:
        """Consume one firing of the first matching fault with budget
        left; None when nothing fires."""
        for i, f in enumerate(self.faults):
            if not f.matches(kind, target, now):
                continue
            if f.count >= 0 and self._fired[i] >= f.count:
                continue
            self._fired[i] += 1
            return f
        return None

    def fired(self) -> int:
        """Point-fault firings plus window faults a component actually
        hit — "how much chaos really landed" for soak reporting."""
        return sum(self._fired) + len(self._observed)

    @staticmethod
    def generate(
        seed: int,
        duration: float,
        executors=(),
        kinds=None,
        events_per_kind: int = 2,
    ) -> "FaultPlan":
        """A random-but-reproducible plan over [0, duration): same seed,
        same plan. Executor faults pick targets from `executors`.

        Defaults to the process-lifecycle kinds so pre-existing seeded
        schedules are stable; pass kinds including NETWORK_FAULT_KINDS
        entries to draw partition windows (network faults target
        executors too — the severed wire is per executor↔server link)."""
        import numpy as np

        rng = np.random.default_rng(seed)
        kinds = tuple(kinds) if kinds is not None else PROCESS_FAULT_KINDS
        executors = list(executors)
        faults = []
        for kind in kinds:
            for _ in range(events_per_kind):
                start = float(rng.uniform(0.0, duration * 0.7))
                window = float(rng.uniform(duration * 0.05, duration * 0.2))
                if (
                    kind.startswith(("executor", "lease", "network"))
                    and executors
                ):
                    target = str(executors[int(rng.integers(len(executors)))])
                else:
                    target = "*"
                count = 2 if kind == "torn_log_write" else -1
                param = float(rng.uniform(0.1, 0.9))
                faults.append(
                    FaultSpec(kind, target, start, window, count, param)
                )
        faults.sort(key=lambda f: (f.start, f.kind, f.target))
        return FaultPlan(faults, seed=seed)


class VirtualClock:
    """Mutable clock shared between the simulator and chaos-aware
    components (ChaosLeader, CrashRecoveringLog): the sim advances `now`,
    everyone else reads it."""

    def __init__(self, now: float = 0.0):
        self.now = now

    def __call__(self) -> float:
        return self.now


class ChaosLeader:
    """Leader-election wrapper honoring `leader_flap` windows: while a
    flap is active this instance is not the leader and previously issued
    tokens fail validation — exactly the mid-cycle-deposed-leader path
    the token protocol guards (scheduler.cycle drops the publish)."""

    def __init__(self, inner, plan: FaultPlan, clock=None):
        self.inner = inner
        self.plan = plan
        self.clock = clock if clock is not None else _time.time

    def _flapping(self) -> bool:
        return self.plan.active("leader_flap", "leader", self.clock()) is not None

    def get_token(self):
        from .leader import LeaderToken

        if self._flapping():
            return LeaderToken(leader=False)
        return self.inner.get_token()

    def validate(self, token) -> bool:
        if self._flapping():
            return False
        return self.inner.validate(token)

    def __call__(self) -> bool:
        return not self._flapping() and self.inner()

    def is_holder(self) -> bool:
        return not self._flapping() and self.inner.is_holder()

    def leader_address(self) -> str:
        return self.inner.leader_address()


class ExponentialBackoff:
    """Exponential backoff with seeded full jitter: delay_k ~ U(0,
    min(cap, base * 2^k)). Seeded so retry schedules are reproducible in
    chaos runs.

    `budget_s` bounds the CUMULATIVE sleep of one retry streak (reset()
    to reset() / success to success): a retrying lease exchange must
    never sleep past the lease it is renewing (lease_ttl), so the last
    delay is clamped to the remaining budget and, once it is spent,
    `exhausted` flips and further delays poll flat at base_s — the lease
    is already dead, so the caller wants prompt reconnection plus
    anti-entropy, not longer sleeps."""

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0, seed: int = 0,
                 budget_s: float | None = None):
        import numpy as np

        self.base_s = base_s
        self.cap_s = cap_s
        self.budget_s = budget_s
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self.attempt = 0
        self.spent_s = 0.0

    @property
    def exhausted(self) -> bool:
        return self.budget_s is not None and self.spent_s >= self.budget_s

    def next_delay(self) -> float:
        ceiling = min(self.cap_s, self.base_s * (2.0 ** self.attempt))
        self.attempt += 1
        delay = float(self._rng.uniform(0.0, ceiling))
        if self.budget_s is not None:
            remaining = self.budget_s - self.spent_s
            if remaining <= 0.0:
                return min(self.base_s, self.cap_s)
            delay = min(delay, remaining)
        self.spent_s += delay
        return delay

    def reset(self) -> None:
        import numpy as np

        self.attempt = 0
        self.spent_s = 0.0
        self._rng = np.random.default_rng(self._seed)


class CircuitOpenError(RuntimeError):
    """Raised by a guarded path while its circuit is open: the RPC
    fast-fails (UNAVAILABLE on the wire, identically on both the JSON and
    proto executor wires) and the caller's backoff loop absorbs it."""


class CircuitBreaker:
    """Per-key circuit breaker (the server's lease path keys by executor
    name): closed -> open after `failure_threshold` consecutive failures;
    after `cooldown_s` one probe is allowed (half-open) — success closes,
    failure re-opens."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 30.0):
        import threading

        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = cooldown_s
        self._failures: dict[str, int] = {}
        self._opened_at: dict[str, float] = {}
        self._probing: set[str] = set()
        # Touched from concurrent gRPC worker threads (one per in-flight
        # lease RPC): check-then-act on the probe set and the failure
        # counters must be atomic.
        self._lock = threading.Lock()

    def _state_locked(self, key: str, now: float) -> str:
        if key not in self._opened_at:
            return "closed"
        if now - self._opened_at[key] >= self.cooldown_s:
            return "half-open"
        return "open"

    def state(self, key: str, now: float | None = None) -> str:
        now = _time.monotonic() if now is None else now
        with self._lock:
            return self._state_locked(key, now)

    def allow(self, key: str, now: float | None = None) -> bool:
        now = _time.monotonic() if now is None else now
        with self._lock:
            state = self._state_locked(key, now)
            if state == "closed":
                return True
            if state == "half-open" and key not in self._probing:
                self._probing.add(key)  # exactly one probe per cooldown
                return True
            return False

    def record_success(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)
            self._opened_at.pop(key, None)
            self._probing.discard(key)

    def record_failure(self, key: str, now: float | None = None) -> None:
        now = _time.monotonic() if now is None else now
        with self._lock:
            count = self._failures.get(key, 0) + 1
            self._failures[key] = count
            self._probing.discard(key)
            if count >= self.failure_threshold:
                self._opened_at[key] = now

    def failures(self, key: str) -> int:
        """Consecutive failures recorded against a key (doctor surface)."""
        with self._lock:
            return self._failures.get(key, 0)


class SolverFaultError(RuntimeError):
    """An injected solver fault: the solve raised mid-round (the
    XLA-runtime-error / device-lost / OOM stand-in)."""


class SolverHangError(SolverFaultError):
    """An injected solver hang past its round budget, surfaced the way a
    watchdog would report it (the in-process seam cannot preempt a truly
    wedged XLA call, so the chaos plan raises the verdict directly)."""


class SolverChaos:
    """Injects solver faults at the kernel seam (scheduler._solve).

    Attached via SchedulerService.attach_solver_chaos; runs on the same
    clock as the rest of the plan (virtual in the simulator). Fault
    targets match failover-ladder rung labels — a fault targeting
    "LOCAL" fails that rung and the ladder retries below it; a "*"
    fault poisons every rung and the round is rejected and requeued.

    `before_solve` fires raise/hang faults; `corrupt` mutates the solve
    output in place (NaN poison into chosen float arrays, wrong-
    placement perturbation of scheduled bindings) and returns the kinds
    applied so callers can account injections.
    """

    def __init__(self, plan: FaultPlan, clock=None):
        self.plan = plan
        self.clock = clock if clock is not None else _time.monotonic
        self.injected: dict[str, int] = {}

    def _note(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def before_solve(self, rung_label: str) -> None:
        now = self.clock()
        if self.plan.fire("solver_raise", rung_label, now) is not None:
            self._note("solver_raise")
            raise SolverFaultError(
                f"injected solver_raise on rung {rung_label!r}"
            )
        if self.plan.fire("solver_hang", rung_label, now) is not None:
            self._note("solver_hang")
            raise SolverHangError(
                f"injected solver_hang on rung {rung_label!r}: solve "
                "exceeded its round budget"
            )

    def corrupt(self, rung_label: str, out: dict) -> list:
        import numpy as np

        now = self.clock()
        applied = []
        if self.plan.fire("solver_nan_poison", rung_label, now) is not None:
            self._note("solver_nan_poison")
            for key in ("fair_share", "uncapped_fair_share"):
                arr = out.get(key)
                if arr is None:
                    continue
                arr = np.array(arr, dtype=np.float64, copy=True)
                if arr.size:
                    arr.flat[0] = np.nan
                out[key] = arr
            applied.append("solver_nan_poison")
        if (
            self.plan.fire("solver_wrong_placement", rung_label, now)
            is not None
        ):
            self._note("solver_wrong_placement")
            sched = np.array(out.get("scheduled_mask"), dtype=bool, copy=True)
            assigned = np.array(out.get("assigned_node"), copy=True)
            if sched.any():
                # Reflect scheduled bindings into invalid negative
                # indices (NO_NODE is -1; anything below is garbage a
                # miscompiled gather could emit — and would silently
                # wrap to the wrong node if committed).
                assigned[sched] = -2 - assigned[sched]
            elif sched.size:
                # Nothing scheduled this round: fabricate a scheduled
                # binding onto a garbage node so the window still lands
                # a detectable fault.
                sched.flat[0] = True
                assigned.flat[0] = -5
                out["scheduled_mask"] = sched
            out["assigned_node"] = assigned
            applied.append("solver_wrong_placement")
        return applied


class CrashRecoveringLog:
    """A FileEventLog whose torn-write faults behave like process crashes.

    Wraps a FileEventLog built with a FaultPlan-driven injector and
    sync_every=1 (so the only record at risk is the one being torn). When
    an append tears, the wrapper reopens the log — recovery truncates the
    torn tail — and retries the publish: the at-least-once redelivery a
    restarted publisher performs. Views keep their reference to the
    wrapper across "crashes"."""

    def __init__(self, directory: str, plan: FaultPlan | None = None,
                 clock=None, target: str = "log", **kwargs):
        self.directory = directory
        self.plan = plan
        self.clock = clock if clock is not None else _time.time
        # Fault target this log answers to ("log" historically; front-door
        # shard WALs use "shard-<i>" so one plan can tear a single shard).
        self.target = target
        self.crashes = 0
        self._suppress_once = False
        kwargs["sync_every"] = 1
        self._kwargs = kwargs
        self._open()

    def _injector(self, data_len: int) -> int | None:
        if self.plan is None or self._suppress_once:
            # The retry immediately after a "crash" must succeed — an
            # unlimited-count torn_log_write spec would otherwise re-fire
            # on every retry and publish() would never terminate (the
            # virtual clock cannot advance inside one publish).
            self._suppress_once = False
            return None
        spec = self.plan.fire("torn_log_write", self.target, self.clock())
        if spec is None:
            return None
        frac = spec.param if 0.0 < spec.param < 1.0 else 0.5
        return max(0, min(data_len - 1, int(data_len * frac)))

    def _open(self):
        from ..events.file_log import FileEventLog

        self._inner = FileEventLog(
            self.directory, fault_injector=self._injector, **self._kwargs
        )

    def publish(self, sequence) -> int:
        from ..events.file_log import InjectedFault

        while True:
            try:
                return self._inner.publish(sequence)
            except InjectedFault:
                self.crashes += 1
                self._suppress_once = True  # the restarted retry lands
                self._open()  # recovery truncates the torn tail

    # -- delegation (the EventLog read surface) --

    def read(self, cursor, limit: int = 1000):
        return self._inner.read(cursor, limit)

    def read_jobset(self, queue, jobset, cursor: int = 0):
        return self._inner.read_jobset(queue, jobset, cursor)

    @property
    def end_offset(self) -> int:
        return self._inner.end_offset

    @property
    def start_offset(self) -> int:
        return self._inner.start_offset

    @property
    def dir(self):
        return self._inner.dir

    def compact(self, up_to: int) -> int:
        return self._inner.compact(up_to)

    def watcher(self):
        return self._inner.watcher()

    def remove_watcher(self, cond):
        return self._inner.remove_watcher(cond)

    def flush(self):
        return self._inner.flush()

    def close(self):
        return self._inner.close()
