"""SLO tracking: multi-window burn rates over declared latency objectives.

Everything before this gated on bit-exactness (replay) or throughput
(bench); nothing gated on what a USER feels — round latency, queue
wait, submit latency. This module is that layer:

- `SLOSpec` (core/config.py): a declared objective over one signal —
  an observation counts GOOD iff value <= threshold_s, and the
  objective is the required good fraction.
- `SLOTracker`: bounded per-SLO event windows with burn rates. Burn
  rate over a window = error_rate / error_budget where error_budget =
  1 - objective; 1.0 means spending the budget exactly at the rate
  that exhausts it at the window's end. The alerting shape is the SRE
  -workbook multiwindow multi-burn-rate rule: a breach requires the
  FAST window (default 5 min at 14x) AND the SLOW window (default 1 h
  at 6x) to both exceed their thresholds — fast-only spikes and
  long-tail noise don't page.
- `evaluate()`: the gate face (tools/slo_gate.py, soak --slo flags):
  over a finite run, an SLO breaches when its lifetime compliance
  falls below the objective or the multiwindow alert fired at any
  observation.

Clock discipline: `observe(..., now=)` takes the caller's clock — the
simulator's virtual time, a soak's virtual clock, or wall time in the
live control plane — so burn windows mean the same thing in every
harness. Values are durations in seconds on whatever signal the SLO
declares; the vocabulary is open (soaks add e.g. shard-lag signals).
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque

from ..core.config import SLOSpec

# The default objectives a tracker runs with when the config declares
# none. round-latency mirrors the reference's 5s maxSchedulingDuration
# guard (config/scheduler/config.yaml:83); frontdoor-p99 mirrors the
# committed frontdoor_soak SLO.
DEFAULT_SLOS = (
    SLOSpec(
        name="round-latency",
        signal="round_seconds",
        threshold_s=5.0,
        objective=0.99,
        description="99% of scheduling cycles complete within 5s (the "
        "reference's maxSchedulingDuration operating point)",
    ),
    SLOSpec(
        name="queue-wait",
        signal="queue_wait_seconds",
        threshold_s=300.0,
        objective=0.95,
        description="95% of jobs receive their first lease within 5 "
        "minutes of submission",
    ),
    SLOSpec(
        name="frontdoor-p99",
        signal="frontdoor_submit_seconds",
        threshold_s=0.25,
        objective=0.99,
        description="99% of submits ack (admission + durable shard-WAL "
        "append) within 250ms",
    ),
)

# Ring-buffer bound per SLO: at one scheduling cycle per second a slow
# window of an hour needs 3600 events; 100k covers every configured
# window at soak rates while bounding memory.
MAX_EVENTS = 100_000


class SLOTracker:
    """Thread-safe (observations arrive from gRPC workers, the cycle
    thread and ingest callbacks); all windows prune lazily on read."""

    def __init__(self, slos=(), metrics=None, clock=None,
                 keep_observations: int = 0):
        self.slos: tuple[SLOSpec, ...] = tuple(slos) or DEFAULT_SLOS
        self.metrics = metrics
        self._clock = clock or _time.time
        self._lock = threading.Lock()
        # keep_observations > 0 retains the raw (signal, value, now)
        # stream (bounded) — the soaks export it as an observation
        # document tools/slo_gate.py re-evaluates offline.
        self._history: deque | None = (
            deque(maxlen=keep_observations) if keep_observations else None
        )
        # slo name -> deque[(ts, good)], bounded at MAX_EVENTS with an
        # explicit prune that maintains the running good count — so
        # compliance is O(1) to read and covers the RETENTION WINDOW,
        # not the process lifetime: a long-running control plane's
        # compliance heals after an incident instead of carrying it
        # forever (finite gate runs under the cap see every event, so
        # the gate semantics are unchanged).
        self._events: dict[str, deque] = {s.name: deque() for s in self.slos}
        self._window_good: dict[str, int] = {s.name: 0 for s in self.slos}
        # Whether the multiwindow alert ever fired (the gate's memory of
        # a mid-run burn even if the tail recovered).
        self._ever_breached: dict[str, float | None] = {
            s.name: None for s in self.slos
        }
        self._by_signal: dict[str, list[SLOSpec]] = {}
        for s in self.slos:
            self._by_signal.setdefault(s.signal, []).append(s)

    @classmethod
    def from_config(cls, config, metrics=None, clock=None) -> "SLOTracker":
        return cls(getattr(config, "slos", ()) or (), metrics=metrics,
                   clock=clock)

    def observes(self, signal: str) -> bool:
        """Whether any declared SLO covers this signal — callers can
        skip measuring entirely when nothing listens."""
        return signal in self._by_signal

    # -- observation ---------------------------------------------------

    def observe(self, signal: str, value: float, now: float | None = None):
        specs = self._by_signal.get(signal)
        if not specs:
            return
        now = self._clock() if now is None else float(now)
        if self._history is not None:
            with self._lock:
                self._history.append((signal, float(value), now))
        m = self.metrics
        for spec in specs:
            good = float(value) <= spec.threshold_s
            with self._lock:
                events = self._events[spec.name]
                events.append((now, good))
                if good:
                    self._window_good[spec.name] += 1
                while len(events) > MAX_EVENTS:
                    _, was_good = events.popleft()
                    if was_good:
                        self._window_good[spec.name] -= 1
            if m is not None and getattr(m, "registry", None) is not None:
                m.slo_events.labels(
                    slo=spec.name, verdict="good" if good else "bad"
                ).inc()
            if not good and self._ever_breached[spec.name] is None:
                # Breach memory can only transition once, and only a bad
                # event can newly fire the alert — so the O(window) burn
                # scans run at most once per bad event UNTIL the first
                # breach and never again (a sustained breach must not
                # turn the submit hot path quadratic).
                burn_fast = self._burn(spec, spec.fast_burn_window_s, now)
                burn_slow = self._burn(spec, spec.slow_burn_window_s, now)
                if (
                    burn_fast >= spec.fast_burn_threshold
                    and burn_slow >= spec.slow_burn_threshold
                ):
                    self._ever_breached[spec.name] = now

    # -- burn math -----------------------------------------------------

    def _burn(self, spec: SLOSpec, window_s: float, now: float) -> float:
        """Error-budget burn rate over the trailing window; 0.0 on an
        empty window."""
        with self._lock:
            events = self._events[spec.name]
            total = bad = 0
            for ts, good in reversed(events):
                if ts < now - window_s:
                    break
                total += 1
                if not good:
                    bad += 1
        if total == 0:
            return 0.0
        budget = max(1e-9, 1.0 - spec.objective)
        return (bad / total) / budget

    def burn_rates(self, now: float | None = None) -> dict:
        """{slo: {"fast": burn, "slow": burn}} over each SLO's windows."""
        now = self._clock() if now is None else float(now)
        return {
            s.name: {
                "fast": round(self._burn(s, s.fast_burn_window_s, now), 3),
                "slow": round(self._burn(s, s.slow_burn_window_s, now), 3),
            }
            for s in self.slos
        }

    def update_metrics(self, now: float | None = None):
        """Refresh the slo_burn_rate / slo_compliance gauges (called
        once per scheduling cycle — burn math is O(window events))."""
        m = self.metrics
        if m is None or getattr(m, "registry", None) is None:
            return
        now = self._clock() if now is None else float(now)
        for s in self.slos:
            m.slo_burn_rate.labels(slo=s.name, window="fast").set(
                self._burn(s, s.fast_burn_window_s, now)
            )
            m.slo_burn_rate.labels(slo=s.name, window="slow").set(
                self._burn(s, s.slow_burn_window_s, now)
            )
            with self._lock:
                good = self._window_good[s.name]
                total = len(self._events[s.name])
            if total:
                m.slo_compliance.labels(slo=s.name).set(good / total)

    # -- reading -------------------------------------------------------

    def snapshot(self, now: float | None = None) -> dict:
        """The `/api/slo` / `armadactl slo` document. Counts and
        compliance cover the retention window (last MAX_EVENTS
        observations per SLO): live status heals after an incident;
        `breached_at` separately remembers a fired multiwindow alert
        for finite-run gates."""
        now = self._clock() if now is None else float(now)
        slos = []
        for s in self.slos:
            with self._lock:
                good = self._window_good[s.name]
                total = len(self._events[s.name])
            bad = total - good
            fast = self._burn(s, s.fast_burn_window_s, now)
            slow = self._burn(s, s.slow_burn_window_s, now)
            slos.append(
                {
                    "name": s.name,
                    "signal": s.signal,
                    "threshold_s": s.threshold_s,
                    "objective": s.objective,
                    "description": s.description,
                    "observed": total,
                    "good": good,
                    "bad": bad,
                    "compliance": round(good / total, 6) if total else None,
                    "burn": {
                        "fast": {
                            "window_s": s.fast_burn_window_s,
                            "rate": round(fast, 3),
                            "threshold": s.fast_burn_threshold,
                        },
                        "slow": {
                            "window_s": s.slow_burn_window_s,
                            "rate": round(slow, 3),
                            "threshold": s.slow_burn_threshold,
                        },
                    },
                    "alerting": (
                        fast >= s.fast_burn_threshold
                        and slow >= s.slow_burn_threshold
                    ),
                    "breached_at": self._ever_breached[s.name],
                }
            )
        return {"slos": slos, "now": now}

    def observations(self) -> list[dict]:
        """The retained raw stream (keep_observations > 0), in the
        tools/slo_gate.py observation-document shape."""
        with self._lock:
            history = list(self._history or ())
        return [
            {"signal": s, "value": v, "now": t} for s, v, t in history
        ]

    # -- the gate face -------------------------------------------------

    def evaluate(self, now: float | None = None) -> dict:
        """Finite-run verdict for tools/slo_gate.py and the soak --slo
        flags: per-SLO breach strings + ok flag. An SLO with zero
        observations is reported but never breaches (a run that simply
        does not exercise a signal must not fail its gate). Compliance
        is over the retention window — identical to lifetime for any
        run under MAX_EVENTS observations per SLO, i.e. every gate use;
        the multiwindow breach memory catches mid-run burns that a
        recovered tail would otherwise hide."""
        snap = self.snapshot(now=now)
        breaches = []
        for s in snap["slos"]:
            if not s["observed"]:
                continue
            if s["compliance"] is not None and s["compliance"] < s["objective"]:
                breaches.append(
                    f"{s['name']}: compliance {s['compliance']:.4f} below "
                    f"objective {s['objective']} "
                    f"({s['bad']}/{s['observed']} over "
                    f"{s['threshold_s']}s on {s['signal']})"
                )
            elif s["breached_at"] is not None:
                breaches.append(
                    f"{s['name']}: multiwindow burn alert fired at "
                    f"t={s['breached_at']:.1f} (fast>="
                    f"{s['burn']['fast']['threshold']}x and slow>="
                    f"{s['burn']['slow']['threshold']}x) even though "
                    "lifetime compliance recovered"
                )
        return {"slos": snap["slos"], "breaches": breaches,
                "ok": not breaches}
