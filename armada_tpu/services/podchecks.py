"""Pod issue detection: configurable stuck/failed pod checks.

Mirrors /root/reference/internal/executor/podchecks/{pod_checks,
event_checks,container_state_checks,action}.go and the pod-issue service
(internal/executor/service/pod_issue_handler.go): pods that sit in a
non-running state too long are examined against configured event-message
and container-state checks, each with a grace period, deciding WAIT,
RETRY (report a retryable run error so the scheduler reschedules) or
FAIL (fatal). The strongest action wins (maxAction, action.go), and a
stuck-terminating expiry force-kills pods that ignore their cancel.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field


class Action(enum.IntEnum):
    WAIT = 0
    RETRY = 1
    FAIL = 2


def max_action(a: Action, b: Action) -> Action:
    """maxAction (action.go): the strongest action wins."""
    return a if a >= b else b


@dataclass(frozen=True)
class EventCheck:
    """One entry of podchecks config `events` (event_checks.go:19-27)."""

    regexp: str
    event_type: str = "Warning"  # "Warning" | "Normal"
    grace_period_s: float = 0.0
    action: Action = Action.RETRY
    inverse: bool = False
    name: str = ""

    def matches(self, event: dict, time_in_state: float) -> bool:
        if event.get("type", "Warning") != self.event_type:
            return False
        hit = re.search(self.regexp, event.get("message", "")) is not None
        if self.inverse == hit:  # inverse XOR match (event_checks.go:90)
            return False
        return time_in_state > self.grace_period_s


@dataclass(frozen=True)
class ContainerStateCheck:
    """One entry of podchecks config `containerStatuses`
    (container_state_checks.go)."""

    state: str  # "waiting"
    reason_regexp: str
    grace_period_s: float = 0.0
    action: Action = Action.RETRY
    inverse: bool = False

    def matches(self, container: dict, time_in_state: float) -> bool:
        if container.get("state") != self.state:
            return False
        hit = re.search(self.reason_regexp, container.get("reason", "")) is not None
        if self.inverse == hit:
            return False
        return time_in_state > self.grace_period_s


@dataclass(frozen=True)
class PodChecksConfig:
    events: tuple[EventCheck, ...] = ()
    container_statuses: tuple[ContainerStateCheck, ...] = ()
    # Pod not assigned to a node within this deadline -> retry
    # (pod_checks.go:81-83).
    deadline_for_node_assignment_s: float = 300.0
    # No status updates at all within this deadline -> node likely bad ->
    # retry (pod_checks.go:85-90).
    deadline_for_updates_s: float = 600.0
    # Cancelled pods that refuse to terminate are force-killed and
    # reported after this (pod_issue_handler.go stuck-terminating expiry).
    stuck_terminating_expiry_s: float = 300.0


DEFAULT_CHECKS = PodChecksConfig(
    events=(
        EventCheck(
            regexp=r"Insufficient .*|node\(s\) didn.t match",
            event_type="Warning",
            grace_period_s=120.0,
            action=Action.RETRY,
            name="unschedulable",
        ),
        EventCheck(
            regexp=r"Failed to pull image|ErrImagePull|ImagePullBackOff",
            event_type="Warning",
            grace_period_s=60.0,
            action=Action.FAIL,
            name="image-pull",
        ),
    ),
    container_statuses=(
        ContainerStateCheck(
            state="waiting",
            reason_regexp="ContainerCreating",
            grace_period_s=600.0,
            action=Action.RETRY,
        ),
        ContainerStateCheck(
            state="waiting",
            reason_regexp="CreateContainerConfigError|InvalidImageName",
            grace_period_s=0.0,
            action=Action.FAIL,
        ),
    ),
)


class PodChecker:
    """PodChecks.GetAction (pod_checks.go:54-110) over our pod records.

    A pod record carries: phase, last_change (ts), node (or ""), events
    (list of {type, message}), containers (list of {state, reason})."""

    def __init__(self, config: PodChecksConfig = DEFAULT_CHECKS):
        self.config = config

    def get_action(self, pod: dict, now: float) -> tuple[Action, str]:
        cfg = self.config
        time_in_state = now - pod.get("last_change", pod.get("created", now))
        messages: list[str] = []

        if not pod.get("node") and time_in_state > cfg.deadline_for_node_assignment_s:
            return (
                Action.RETRY,
                f"pod not assigned to a node within "
                f"{cfg.deadline_for_node_assignment_s}s deadline",
            )

        events = pod.get("events", ())
        containers = pod.get("containers", ())
        if (
            not events
            and not containers
            and time_in_state > cfg.deadline_for_updates_s
        ):
            return (
                Action.RETRY,
                f"pod received no updates within {cfg.deadline_for_updates_s}s"
                " — node likely bad",
            )

        result = Action.WAIT
        for event in events:
            for check in cfg.events:  # first matching check decides
                if check.matches(event, time_in_state):
                    result = max_action(result, check.action)
                    messages.append(
                        f"event check {check.name or check.regexp}: "
                        f"{event.get('message', '')}"
                    )
                    break
        for container in containers:
            for check in cfg.container_statuses:
                if check.matches(container, time_in_state):
                    result = max_action(result, check.action)
                    messages.append(
                        f"container {container.get('state')}/"
                        f"{container.get('reason')}"
                    )
                    break
        return result, "\n".join(messages)


class PodIssueHandler:
    """The pod-issue service loop (service/pod_issue_handler.go): walks
    non-running pods, applies the checker, and turns RETRY/FAIL actions
    into run-error reports; expires stuck-terminating pods."""

    def __init__(self, checker: PodChecker | None = None):
        self.checker = checker or PodChecker()
        self.terminating: dict[str, float] = {}  # run_id -> kill time

    def note_kill(self, run_id: str, now: float):
        self.terminating.setdefault(run_id, now)

    def note_gone(self, run_id: str):
        self.terminating.pop(run_id, None)

    def examine(self, pods: dict[str, dict], now: float) -> list[dict]:
        """Returns issue reports: {run_id, action, message, retryable}.
        Pods in phase created/pending are candidates; running pods are
        healthy by definition (the reference only checks pre-running and
        terminating states)."""
        issues = []
        for run_id, pod in pods.items():
            if pod.get("phase") not in ("created", "pending"):
                continue
            action, message = self.checker.get_action(pod, now)
            if action == Action.WAIT:
                continue
            issues.append(
                {
                    "run_id": run_id,
                    "action": action,
                    "message": message or "pod issue detected",
                    "retryable": action == Action.RETRY,
                }
            )
        # Stuck-terminating expiry: the pod was cancelled but still exists.
        expiry = self.checker.config.stuck_terminating_expiry_s
        for run_id, killed_at in list(self.terminating.items()):
            if run_id not in pods:
                self.terminating.pop(run_id, None)
                continue
            if now - killed_at > expiry:
                issues.append(
                    {
                        "run_id": run_id,
                        "action": Action.RETRY,
                        "message": f"pod stuck terminating for >{expiry}s; "
                        "force deleting",
                        "retryable": True,
                        "force_delete": True,
                    }
                )
        return issues
