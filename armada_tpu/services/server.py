"""Single-process control-plane assembly.

Wires the full stack the way the reference's `mage dev:up fake-executor`
does (server + scheduler + ingesters + fake executors, zero Kubernetes):
event log, scheduler cycle loop, submission API, query API, reports,
metrics, gRPC endpoint. One process; every component is the same object the
distributed deployment uses.
"""

from __future__ import annotations

import os
import threading
import time as _time

from ..core.config import SchedulingConfig
from ..events import InMemoryEventLog
from .fake_executor import FakeExecutor, make_nodes
from .grpc_api import ApiServer
from .leader import StandaloneLeader
from .metrics import SchedulerMetrics, serve_metrics
from .queryapi import QueryApi
from .scheduler import SchedulerService
from .submit import SubmitService
from .submit_check import SubmitChecker


class ControlPlane:
    def __init__(
        self,
        config: SchedulingConfig | None = None,
        *,
        backend: str = "oracle",
        # Sharded-solve mesh spec for the kernel backend: int (1D chip
        # count), "HxC" / (hosts, chips) (two-level ICI+DCN hierarchy,
        # parallel/multihost.py), or a jax Mesh. None = unsharded.
        mesh=None,
        cycle_period: float = 1.0,
        grpc_port: int = 0,
        metrics_port: int | None = None,
        lookout_port: int | None = None,
        health_port: int | None = None,
        fake_executors: list[dict] | None = None,
        enable_submit_check: bool = False,
        data_dir: str | None = None,
        tls: tuple | None = None,
    ):
        self.config = config or SchedulingConfig()
        self.checkpoints = None
        if data_dir:
            from ..events.file_log import FileEventLog
            from .checkpoint import CheckpointManager, CheckpointStore

            self.log = FileEventLog(data_dir)
            store = CheckpointStore(os.path.join(data_dir, "checkpoints"))
            self.checkpoints = CheckpointManager(store, self.log)
        else:
            self.log = InMemoryEventLog()

        def _ckpt(name):
            return (
                self.checkpoints.store.load(name) if self.checkpoints else None
            )

        self.leader = StandaloneLeader()
        # Store backpressure (services/backpressure.py; the reference's
        # etcd health monitoring): gates submissions and executor pod
        # creation when the log backs up. Signals are config-gated.
        self.store_health = None
        if self.config.store_capacity_bytes or self.config.max_ingest_lag_events:
            from .backpressure import StoreHealthMonitor

            self.store_health = StoreHealthMonitor(
                self.log,
                capacity_bytes=self.config.store_capacity_bytes,
                fraction_of_capacity_limit=(
                    self.config.store_fraction_of_capacity_limit
                ),
                max_ingest_lag_events=self.config.max_ingest_lag_events,
            )
        self.scheduler = SchedulerService(
            self.config, self.log, backend=backend, mesh=mesh,
            is_leader=self.leader, checkpoint=_ckpt("scheduler"),
        )
        # Solver autopilot (armada_tpu/autotune): the tuning store is
        # restored from its checkpoint first, then any config-named
        # offline profile (tools/autotune.py output) overlays it — the
        # config is the operator's override. The scheduler then adopts
        # the store's per-pool vector at its first round.
        self.autotune = None
        if self.config.autotune_enabled:
            from ..autotune import AutotuneController

            self.autotune = AutotuneController(self.config)
            ck = _ckpt("autotune")
            if ck is not None:
                self.autotune.store.load(ck[1])
            if self.config.autotune_profile:
                try:
                    # operator=True: the config-named profile outranks
                    # checkpoint-restored online adoptions in lookup —
                    # config is the operator's override, every boot it
                    # is configured.
                    self.autotune.store.merge_json(
                        self.config.autotune_profile, operator=True
                    )
                except Exception as e:  # noqa: BLE001 - tuning is advisory
                    print(f"autotune profile load failed: {e!r}")
            self.scheduler.attach_autotune(self.autotune)
        # Submit-side shedding consumes store capacity AND round-deadline
        # pressure (repeated maxSchedulingDuration truncations) through one
        # gate: sustained overload sheds intake instead of growing the
        # backlog unboundedly.
        from .backpressure import CompositeGate

        self.submit_gate = CompositeGate(
            self.store_health, self.scheduler.round_pressure
        )
        self.metrics = SchedulerMetrics()
        self.scheduler.attach_metrics(self.metrics)
        # SLO layer (services/slo.py): declared (or default) objectives
        # over round latency / queue wait / submit latency, tracked with
        # multi-window burn rates — surfaced via GET /api/slo, the
        # SLOStatus RPC (`armadactl slo`) and scheduler_slo_* metrics.
        from .slo import SLOTracker

        self.slo = SLOTracker.from_config(self.config, metrics=self.metrics)
        self.scheduler.attach_slo(self.slo)
        # Front door (armada_tpu/frontdoor): jobset-keyed sharded ingest
        # WALs (the ack point; exactly-once delivery into the log) with
        # per-tenant admission layered in front of the SAME composite
        # gate — during overload the gate's reason drives quota-weighted
        # shedding instead of the submit service's all-or-nothing check.
        # Quota weight is the fair-share weight (1/priorityFactor), read
        # lazily from the queue registry so `armadactl queue update`
        # adjusts a tenant's slice live (the overload runbook's lever).
        self.frontdoor = None
        if self.config.frontdoor_shards > 0:
            from ..frontdoor import FrontDoor, TenantAdmission

            def _quota(tenant: str) -> float:
                q = self.submit.get_queue(tenant)
                return q.spec.weight if q is not None else 1.0

            admission = TenantAdmission(
                tenant_rate=self.config.frontdoor_tenant_rate,
                tenant_burst=self.config.frontdoor_tenant_burst,
                global_rate=self.config.frontdoor_global_rate,
                global_burst=self.config.frontdoor_global_burst,
                overload_rate=self.config.frontdoor_overload_rate,
                downstream=self.submit_gate,
                quota_of=_quota,
                metrics=self.metrics,
            )
            self.frontdoor = FrontDoor(
                self.log,
                num_shards=self.config.frontdoor_shards,
                directory=(
                    os.path.join(data_dir, "frontdoor") if data_dir else None
                ),
                admission=admission,
                metrics=self.metrics,
            )
        self.submit = SubmitService(
            self.config, self.log, scheduler=self.scheduler,
            checkpoint=_ckpt("submit"), store_health=self.submit_gate,
            frontdoor=self.frontdoor, slo=self.slo,
        )
        if self.store_health is not None:
            self.store_health.add_lag_source(
                "scheduler-ingester",
                lambda: max(
                    0, self.log.end_offset - self.scheduler.ingester.cursor
                ),
            )
            if self.frontdoor is not None:
                # Shard lag is ingest lag too: acked-but-undelivered work
                # backs the store up just like an unsynced view.
                self.store_health.add_lag_source(
                    "frontdoor", self.frontdoor.max_lag
                )
        self.query = QueryApi(
            self.scheduler.jobdb, timeline=self.scheduler.timeline
        )
        # What-if planner (armada_tpu/whatif): fork capture on the round
        # seam + bounded shadow-solve worker; the WhatIf/PlanDrain/
        # ExecuteDrain RPCs and lookout's /api/whatif reach it through
        # the scheduler.
        from ..whatif import WhatIfService

        self.whatif = WhatIfService(
            self.scheduler, metrics=self.metrics,
            cycle_interval=cycle_period,
        )
        self.scheduler.attach_whatif(self.whatif)
        self.submit_checker = (
            SubmitChecker(self.config, self.scheduler) if enable_submit_check else None
        )
        self.cycle_period = cycle_period

        self.executors: list[FakeExecutor] = []
        for spec in fake_executors or []:
            self.executors.append(
                FakeExecutor(
                    spec.get("name", f"fake-{len(self.executors)}"),
                    self.log,
                    self.scheduler,
                    nodes=make_nodes(
                        spec.get("name", f"fake-{len(self.executors)}"),
                        count=int(spec.get("nodes", 10)),
                        pool=spec.get("pool", "default"),
                        cpu=str(spec.get("cpu", "8")),
                        memory=str(spec.get("memory", "128Gi")),
                        labels=spec.get("labels"),
                        extra_resources=spec.get("extra_resources"),
                    ),
                    pool=spec.get("pool", "default"),
                    runtime_for=lambda job_id, rt=float(spec.get("runtime", 30.0)): rt,
                )
            )

        from .binoculars import BinocularsService

        self.binoculars = BinocularsService(self.scheduler, self.executors)
        # Per-jobset event-stream view (the event-ingester's Redis streams,
        # eventingester/store/eventstore.go): watchers read partitioned
        # streams instead of scanning the shared log.
        from .event_index import EventStreamIndex

        self.event_index = EventStreamIndex(
            self.log, checkpoint=_ckpt("event_index")
        )
        self.api = ApiServer(
            self.submit,
            self.scheduler,
            self.query,
            self.log,
            self.submit_checker,
            binoculars=self.binoculars,
            event_index=self.event_index,
            store_health=self.store_health,
            frontdoor=self.frontdoor,
        )
        self.grpc_server, self.grpc_port = self.api.serve(grpc_port, tls=tls)
        self.metrics_server, self.metrics_port = (
            serve_metrics(self.metrics, metrics_port)
            if metrics_port is not None
            else (None, None)
        )
        # Independent lookout materialization (the reference's third
        # ingester): its own cursor + rows, synced in the loop; the lookout
        # UI queries it, never the scheduler's jobdb.
        from .lookout_ingester import LookoutStore

        self.lookout_store = LookoutStore(
            self.log, error_rules=self.config.error_categories,
            checkpoint=_ckpt("lookout"),
        )
        if self.checkpoints is not None:
            # Every log consumer that replays on restart must be
            # registered: compaction trails the min checkpointed cursor.
            self.checkpoints.register("scheduler", self.scheduler)
            self.checkpoints.register("submit", self.submit)
            self.checkpoints.register("event_index", self.event_index)
            self.checkpoints.register("lookout", self.lookout_store)
            if self.frontdoor is not None:
                # The shard ingesters' recovery scan starts at their
                # durably saved main-log offsets (drain.json, not the
                # checkpoint store) — register the front door so
                # compaction never deletes the dedup window out from
                # under a restarting shard (idle shards report the log
                # end, not 0, so they cannot stall compaction).
                self.checkpoints.register("frontdoor", self.frontdoor)
        self.lookout = None
        if lookout_port is not None:
            from .lookout_http import LookoutHttpServer

            self.lookout = LookoutHttpServer(
                QueryApi(
                    lookout=self.lookout_store,
                    timeline=self.scheduler.timeline,
                ),
                self.scheduler,
                self.submit,
                lookout_port,
                binoculars=self.binoculars,
                frontdoor=self.frontdoor,
            )
        # Health surface (common/health; schedulerapp.go:71-75).
        from .health import (
            BackpressureChecker,
            FencedExecutorChecker,
            FuncChecker,
            HeartbeatChecker,
            MultiChecker,
            SolverLadderChecker,
            StartupCompleteChecker,
            serve_health,
        )

        self.startup_checker = StartupCompleteChecker()
        self.cycle_checker = HeartbeatChecker(
            "cycle", timeout_s=max(30.0, 20 * cycle_period)
        )
        checkers = [
            self.startup_checker,
            self.cycle_checker,
            FuncChecker(
                "lookout-lag",
                lambda: (
                    self.lookout_store.lag_events < 100_000,
                    f"lag {self.lookout_store.lag_events} events",
                ),
            ),
        ]
        if self.store_health is not None:
            self.store_health.add_lag_source(
                "lookout", lambda: self.lookout_store.lag_events
            )
            checkers.append(
                BackpressureChecker("store", self.store_health)
            )
        # Round-deadline pressure surfaces in /health as ADVISORY detail:
        # a pool truncating round after round is degraded (and sheds
        # intake via the submit gate above), but it is live and making
        # bounded progress — it must not trip the liveness probe into a
        # restart loop (services/backpressure.RoundDeadlinePressure).
        checkers.append(
            BackpressureChecker(
                "round-deadline", self.scheduler.round_pressure,
                advisory=True,
            )
        )
        # Lease fencing is advisory detail too: a fenced executor means
        # the split-brain protocol is WORKING (stale exchanges rejected
        # until its anti-entropy sync) — name it for operators without
        # tripping liveness.
        checkers.append(FencedExecutorChecker(self.scheduler))
        # The solve ladder is advisory as well: open breakers and recent
        # round rejections mean the firewall/failover containment is
        # doing its job — surface them, don't restart over them.
        checkers.append(SolverLadderChecker(self.scheduler))
        self.health = MultiChecker(*checkers)
        self.health_server = None
        if health_port is not None:
            self.health_server, self.health_port = serve_health(
                self.health, self.startup_checker, health_port
            )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._maintenance_lock = threading.Lock()
        self.tasks = None  # BackgroundTaskManager, created by start()

    def _loop(self):
        while not self._stop.is_set():
            now = _time.time()
            if self.frontdoor is not None:
                # Drain the shard WALs into the log BEFORE the cycle so
                # this round sees everything acked up to now; injected
                # shard crashes restart in place inside pump().
                self.frontdoor.pump(now=now)
            for ex in self.executors:
                ex.tick(now)
            try:
                # The maintenance lock serializes checkpointing with the
                # cycle (checkpoint_state must not observe a cursor from
                # before a sync whose effects it dumps — the two ran
                # inline in this loop before the task manager existed).
                with self._maintenance_lock:
                    self.scheduler.cycle(now=now)
                self.cycle_checker.beat()
            except Exception as e:  # keep the loop alive; next cycle retries
                print(f"cycle error: {e!r}")
            self.lookout_store.sync()
            # scheduler_cycle_seconds is observed inside
            # SchedulerService.cycle itself — simulator-driven cycles
            # tick it too, not only this loop.
            self._stop.wait(self.cycle_period)

    def _prune_views(self):
        """Retention: the lookout pruner (internal/lookout/pruner) + the
        event ingester's per-jobset stream expiry."""
        cutoff = _time.time() - self.config.terminal_job_retention_s
        self.lookout_store.prune(cutoff)
        self.event_index.prune(cutoff)

    def _checkpoint_views(self):
        """Bounded restart + bounded disk: checkpoint all views, drop log
        segments they have all materialized (services/checkpoint.py).
        Serialized against the scheduler cycle (see _loop)."""
        with self._maintenance_lock:
            self.submit.sync()
            self.event_index.sync()
            self._save_autotune()
            self.checkpoints.checkpoint_and_compact()

    def _save_autotune(self):
        """Persist the tuning store next to the view checkpoints. NOT a
        registered view: it consumes no log events, so its (meaningless)
        cursor must never hold back log compaction."""
        if self.autotune is not None and self.checkpoints is not None:
            self.checkpoints.store.save(
                "autotune", 0, self.autotune.store.dump()
            )

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        # Maintenance loops run under the background task manager
        # (common/task BackgroundTaskManager): named, panic-contained,
        # duration-observed, joined on stop.
        from ..utils.tasks import BackgroundTaskManager

        maintenance_interval = max(30.0, 600 * self.cycle_period)
        self.tasks = BackgroundTaskManager()
        self.tasks.register(self._prune_views, maintenance_interval, "prune")
        if self.checkpoints is not None:
            self.tasks.register(
                self._checkpoint_views, maintenance_interval, "checkpoint"
            )
        self.startup_checker.mark_complete()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        stragglers: list = []
        if self.tasks is not None:
            stragglers = self.tasks.stop_all(timeout=5.0)
            if stragglers:
                print(f"background tasks still running: {stragglers}")
        if self.checkpoints is not None and "checkpoint" not in stragglers:
            # Clean shutdown writes a final checkpoint so the next start
            # replays (near-)nothing; a kill-9 still recovers from the
            # last periodic checkpoint + suffix replay. Skipped if the
            # periodic checkpoint task straggled past its join timeout —
            # two writers on the same .tmp files would tear both.
            try:
                with self._maintenance_lock:
                    self.submit.sync()
                    self.event_index.sync()
                    self._save_autotune()
                    self.checkpoints.save_all()
            except Exception as e:
                print(f"final checkpoint failed: {e!r}")
        self.grpc_server.stop(grace=0.5)
        if self.metrics_server:
            self.metrics_server.shutdown()
        if self.lookout:
            self.lookout.stop()
        if self.health_server:
            self.health_server.shutdown()
        if self.frontdoor is not None:
            self.frontdoor.close()
        if hasattr(self.log, "close"):
            self.log.close()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.grpc_port}"
