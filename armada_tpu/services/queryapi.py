"""Job query API: the Lookout data surface.

The reference materializes a denormalized lookout Postgres schema and
serves a REST API with rich filtering/grouping/aggregation
(/root/reference/internal/lookout/repository/{getjobs,groupjobs}.go and
internal/server/queryapi). Here the same query surface runs over the jobdb
materialization directly (the log is the source of truth either way); the
REST/gRPC transport wraps this object.

Supported: field filters (exact/any-of/prefix), ordering, pagination,
group-by with counts and aggregates — the operations the Lookout UI issues.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

from ..jobdb import JobDb, JobState


@dataclass(frozen=True)
class JobFilter:
    """One predicate, mirroring the reference's model.Filter
    (lookout/model/model.go:8-16 match constants; querybuilder.go:616-650
    operator translation). With is_annotation the field names an
    annotation key instead of a column."""

    field: str  # queue | jobset | state | job_id | priority_class | ...
    value: object = None
    match: str = "exact"  # exact | anyOf | startsWith | contains |
    #  greaterThan | lessThan | greaterThanOrEqualTo | lessThanOrEqualTo |
    #  exists
    is_annotation: bool = False


@dataclass(frozen=True)
class Order:
    field: str = "submitted"  # submitted | job_id | priority | state | ...
    direction: str = "asc"


@dataclass
class JobRow:
    job_id: str
    queue: str
    jobset: str
    state: str
    priority: int
    priority_class: str
    submitted: float
    node: str
    executor: str
    attempts: int
    error: str
    error_category: str
    last_transition: float = 0.0
    runtime_s: float = 0.0  # latest run start -> finish (0 while running)
    run_id: str = ""  # latest run
    annotations: dict = field(default_factory=dict)

    @staticmethod
    def from_job(job) -> "JobRow":
        kw = {f: _value_job(job, f) for f in JobRow.__dataclass_fields__}
        # Own copy: the accessor returns the live spec dict by reference
        # (cheap on the filter hot path); a returned row must not alias
        # load-bearing scheduler state.
        kw["annotations"] = dict(kw["annotations"])
        return JobRow(**kw)

    @staticmethod
    def from_lookout(row) -> "JobRow":
        kw = {f: _value_lookout(row, f) for f in JobRow.__dataclass_fields__}
        kw["annotations"] = dict(kw["annotations"])
        return JobRow(**kw)

_JOB_FIELDS = frozenset(JobRow.__dataclass_fields__)


def _check_field(field: str) -> str:
    """Queryable fields are exactly the JobRow schema — identical on both
    backends. Unknown (or backend-private) fields are rejected loudly so
    GET /api/jobs?order=typo is a 400, not a silent None-sort."""
    if field not in _JOB_FIELDS:
        raise ValueError(f"unknown field {field!r}")
    return field


def _runtime_s(started, finished) -> float:
    return max(0.0, finished - started) if started and finished else 0.0


def _value_job(obj, field: str):
    """Field accessor over a raw jobdb Job. Queries filter/sort/aggregate
    through these accessors and materialize JobRow dataclasses only for
    the returned page (the reference pushes this down to SQL; building
    100k+ row objects per query was the Python equivalent of a full table
    scan with materialization). JobRow.from_job builds from the SAME
    accessor, so page values can never disagree with filter/sort values."""
    if field == "job_id":
        return obj.id
    if field == "state":
        return obj.state.value
    if field == "priority_class":
        return obj.spec.priority_class
    if field == "annotations":
        return obj.spec.annotations
    if field in ("node", "executor", "run_id", "attempts", "runtime_s",
                 "last_transition"):
        run = obj.latest_run
        if field == "node":
            return run.node_id if run else ""
        if field == "executor":
            return run.executor if run else ""
        if field == "run_id":
            return run.id if run else ""
        if field == "attempts":
            return obj.num_attempts
        if field == "runtime_s":
            return _runtime_s(run.started, run.finished) if run else 0.0
        return max(
            obj.submitted,
            run.finished if run else 0.0,
            run.started if run else 0.0,
            run.leased if run else 0.0,
        )
    return getattr(obj, _check_field(field))


def _value_lookout(obj, field: str):
    """Field accessor over a raw LookoutRow (see _value_job)."""
    if field in ("node", "executor", "run_id", "attempts", "runtime_s"):
        run = obj.latest_run
        if field == "attempts":
            return len(obj.runs)
        if field == "runtime_s":
            return _runtime_s(run.started, run.finished) if run else 0.0
        if run is None:
            return ""
        return {"node": run.node, "executor": run.executor,
                "run_id": run.run_id}[field]
    return getattr(obj, _check_field(field))


def _matches_raw(value, obj, f: JobFilter) -> bool:
    if f.is_annotation:
        annotations = value(obj, "annotations") or {}
        present = f.field in annotations
        if f.match == "exists":
            return present
        if not present:
            return False
        actual = annotations[f.field]
    else:
        actual = value(obj, f.field)
        if f.match == "exists":
            return actual not in (None, "")
    if f.match == "exact":
        return actual == f.value
    if f.match == "anyOf":
        return actual in f.value
    if f.match == "startsWith":
        return isinstance(actual, str) and actual.startswith(str(f.value))
    if f.match == "contains":
        return isinstance(actual, str) and str(f.value) in actual
    if f.match == "greaterThan":
        return actual is not None and actual > f.value
    if f.match == "lessThan":
        return actual is not None and actual < f.value
    if f.match == "greaterThanOrEqualTo":
        return actual is not None and actual >= f.value
    if f.match == "lessThanOrEqualTo":
        return actual is not None and actual <= f.value
    raise ValueError(f"unknown match {f.match!r}")


class QueryApi:
    """Query surface over either the live jobdb or the independently
    materialized lookout view (pass `lookout=LookoutStore`): the reference
    serves lookout queries from its own Postgres view, never the scheduler
    DB (internal/lookout/repository)."""

    def __init__(self, jobdb: JobDb | None = None, lookout=None,
                 timeline=None):
        assert jobdb is not None or lookout is not None
        self.jobdb = jobdb
        self.lookout = lookout
        # Optional job-journey ledger (services/job_timeline.py): the
        # per-job transition + unschedulable-round history behind
        # job_trace(); None on deployments without a scheduler in
        # process (pure lookout readers).
        self.timeline = timeline
        # One accessor bound per backend (no per-row type sniffing on the
        # query hot path).
        self._value = _value_lookout if lookout is not None else _value_job

    def _raw_rows(self) -> list:
        if self.lookout is not None:
            return self.lookout.all_rows()
        return self.jobdb.read_txn().all_jobs()

    def _to_rows(self, page) -> list[JobRow]:
        """Materialize the returned page. Lookout rows mutate in place
        under the ingester; converting under the store lock keeps each
        returned row internally consistent. (A row may have stopped
        matching the filters between scan and materialization — the view
        is eventually consistent, like any UI read of a live system.)"""
        if self.lookout is not None:
            return self.lookout.materialize(page, JobRow.from_lookout)
        return [JobRow.from_job(o) for o in page]

    def get_jobs(
        self,
        filters: list[JobFilter] = (),
        order: Order = Order(),
        skip: int = 0,
        take: int = 100,
    ) -> tuple[list[JobRow], int]:
        """Filtered, ordered, paginated rows + total match count. Filter
        and sort run on RAW rows; JobRow materialization happens for the
        returned page only (at 100k+ rows, per-query dataclass
        construction was seconds of latency)."""
        value = self._value
        _check_field(order.field)
        if self.lookout is not None and hasattr(self.lookout, "query_rows"):
            # Persistent stores translate filter/sort/page to SQL
            # (querybuilder.go); None = not expressible, fall through to
            # the generic scan.
            pushed = self.lookout.query_rows(filters, order, skip, take)
            if pushed is not None:
                page, total = pushed
                return self._to_rows(page), total
        rows = [
            obj
            for obj in self._raw_rows()
            if all(_matches_raw(value, obj, f) for f in filters)
        ]
        # Deterministic total order: job_id is the secondary key and
        # follows the primary direction — a persistent store can then
        # serve either direction with a single composite index scan
        # (reversing an index reverses every column together).
        keyf = lambda obj: (value(obj, order.field), value(obj, "job_id"))
        top = skip + take
        if 0 < top < len(rows) // 4:
            # Heap-select the page: O(N log K) beats a full O(N log N)
            # sort when the page is a sliver of the match set (the UI's
            # common shape: first pages of a 100k+ row table).
            sel = heapq.nlargest if order.direction == "desc" else heapq.nsmallest
            page = sel(top, rows, key=keyf)[skip:]
        else:
            rows.sort(key=keyf, reverse=(order.direction == "desc"))
            page = rows[skip : skip + take]
        return self._to_rows(page), len(rows)

    def group_jobs(
        self,
        group_by: str,
        filters: list[JobFilter] = (),
        aggregates: list = (),
        group_by_annotation: bool = False,
        order_by: str = "count",
        direction: str = "desc",
        skip: int = 0,
        take: int = 0,
    ) -> list[dict]:
        """Counts (+ aggregates) per group value (groupjobs.go).

        group_by names a column, or an annotation key with
        group_by_annotation (rows missing the key are excluded, matching
        the reference's implicit exists-filter, querybuilder.go:273).
        Aggregates: legacy strings ("submitted_min", "state_counts", ...)
        or reference-style dicts {"field": col, "type": "min|max|average"}
        (aggregates.go GetAggregatorsForColumn). Groups are ordered by
        order_by ("count", "name", or an aggregate name) and paginated
        when take > 0."""
        groups: dict = {}
        agg_specs = []
        for agg in aggregates:
            if isinstance(agg, dict):
                agg_specs.append((f"{agg['field']}_{agg['type']}",
                                  agg["field"], agg["type"]))
            else:
                agg_specs.append((agg, None, None))
        value = self._value
        if not group_by_annotation:
            _check_field(group_by)
        pushed = None
        if (
            not group_by_annotation
            and self.lookout is not None
            and hasattr(self.lookout, "group_rows")
        ):
            pushed = self.lookout.group_rows(group_by, filters, agg_specs)
        if pushed is not None:
            groups = pushed
        else:
            groups = self._group_scan(
                groups, agg_specs, group_by, group_by_annotation, filters
            )
        for g in groups.values():
            for name, v in list(g["aggregates"].items()):
                if isinstance(v, dict) and set(v) == {"sum", "n"}:
                    g["aggregates"][name] = v["sum"] / v["n"] if v["n"] else 0.0
        out = list(groups.values())
        if order_by == "count":
            key = lambda g: g["count"]
        elif order_by == "name":
            key = lambda g: g["name"]
        else:
            key = lambda g: g["aggregates"].get(order_by, 0)
        # Deterministic ties: group name is the secondary key, so the
        # scan path and a SQL GROUP BY pushdown order identically.
        out.sort(key=lambda g: str(g["name"]))
        out.sort(key=key, reverse=(direction == "desc"))
        if skip:
            out = out[skip:]
        if take:
            out = out[:take]
        return out

    def _group_scan(self, groups, agg_specs, group_by, group_by_annotation, filters):
        value = self._value
        for row in self._raw_rows():
            if not all(_matches_raw(value, row, f) for f in filters):
                continue
            if group_by_annotation:
                annotations = value(row, "annotations") or {}
                if group_by not in annotations:
                    continue
                key = annotations[group_by]
            else:
                key = value(row, group_by)
            g = groups.setdefault(
                key, {"name": key, "count": 0, "aggregates": {}}
            )
            g["count"] += 1
            state = value(row, "state")
            for agg, col, typ in agg_specs:
                if col is not None:
                    val = value(row, col)
                    if typ == "min":
                        cur = g["aggregates"].get(agg)
                        g["aggregates"][agg] = (
                            val if cur is None else min(cur, val)
                        )
                    elif typ == "max":
                        cur = g["aggregates"].get(agg)
                        g["aggregates"][agg] = (
                            val if cur is None else max(cur, val)
                        )
                    elif typ == "average":
                        bucket = g["aggregates"].setdefault(
                            agg, {"sum": 0.0, "n": 0}
                        )
                        bucket["sum"] += float(val or 0.0)
                        bucket["n"] += 1
                    elif typ == "state_counts":
                        sc = g["aggregates"].setdefault(agg, {})
                        sc[state] = sc.get(state, 0) + 1
                    else:
                        raise ValueError(f"unknown aggregate type {typ!r}")
                elif agg == "submitted_min":
                    cur = g["aggregates"].get(agg)
                    sub = value(row, "submitted")
                    g["aggregates"][agg] = (
                        sub if cur is None else min(cur, sub)
                    )
                elif agg == "submitted_max":
                    cur = g["aggregates"].get(agg)
                    sub = value(row, "submitted")
                    g["aggregates"][agg] = (
                        sub if cur is None else max(cur, sub)
                    )
                elif agg == "state_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    sc[state] = sc.get(state, 0) + 1
                elif agg == "error_category_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    cat = value(row, "error_category")
                    if cat:
                        sc[cat] = sc.get(cat, 0) + 1
                elif agg == "last_transition_max":
                    cur = g["aggregates"].get(agg)
                    lt = value(row, "last_transition")
                    g["aggregates"][agg] = (
                        lt if cur is None else max(cur, lt)
                    )
                elif agg == "runtime_avg":
                    bucket = g["aggregates"].setdefault(agg, {"sum": 0.0, "n": 0})
                    rt = value(row, "runtime_s")
                    if rt:
                        bucket["sum"] += rt
                        bucket["n"] += 1
        return groups

    def get_job_errors(
        self, filters: list[JobFilter] = (), take: int = 100
    ) -> list[dict]:
        """Error drilldown (lookout repository GetJobError + the UI's error
        surfacing): failed jobs with error text + category + run history."""
        value = self._value
        out = []
        for row in self._raw_rows():
            if not value(row, "error"):
                continue
            if not all(_matches_raw(value, row, f) for f in filters):
                continue
            out.append(
                {
                    name: value(row, name)
                    for name in (
                        "job_id", "queue", "jobset", "state", "error",
                        "error_category", "attempts", "node",
                    )
                }
            )
            if len(out) >= take:
                break
        return out

    def job_details(self, job_id: str) -> dict | None:
        """Job drill-down for the UI: spec + run history + error."""
        if self.lookout is not None:
            row = self.lookout.get(job_id)
            if row is None:
                return None
            return {
                "job_id": row.job_id,
                "queue": row.queue,
                "jobset": row.jobset,
                "state": row.state,
                "priority": row.priority,
                "priority_class": row.priority_class,
                "requests": dict(row.requests),
                "annotations": dict(row.annotations),
                "submitted": row.submitted,
                "error": row.error,
                "error_category": row.error_category,
                "runs": [
                    {
                        "run_id": r.run_id,
                        "executor": r.executor,
                        "node": r.node,
                        "state": r.state,
                        "leased": r.leased,
                        "started": r.started,
                        "finished": r.finished,
                        "error": r.error,
                        "debug": r.debug,
                        "termination_reason": r.termination_reason,
                    }
                    for r in row.runs
                ],
            }
        job = self.jobdb.get(job_id)
        if job is None:
            return None
        return {
            "job_id": job.id,
            "queue": job.queue,
            "jobset": job.jobset,
            "state": job.state.value,
            "priority": job.priority,
            "priority_class": job.spec.priority_class,
            "requests": dict(job.spec.requests),
            "annotations": dict(job.spec.annotations),
            "submitted": job.submitted,
            "error": job.error,
            "error_category": job.error_category,
            "runs": [
                {
                    "run_id": r.id,
                    "executor": r.executor,
                    "node": r.node_id,
                    "state": r.state.value,
                    "leased": r.leased,
                    "started": r.started,
                    "finished": r.finished,
                }
                for r in job.runs
            ],
        }

    def job_trace(self, job_id: str) -> dict | None:
        """The job's journey (timeline + rendered text), or None when no
        ledger is attached or the job was never observed."""
        if self.timeline is None:
            return None
        doc = self.timeline.get(job_id)
        if doc is None:
            return None
        return {
            "journey": doc,
            "rendered": self.timeline.render(job_id, doc=doc),
        }

    def get_job_spec(self, job_id: str):
        job = self.jobdb.get(job_id)
        return job.spec if job else None

    def get_job_runs(self, job_id: str):
        job = self.jobdb.get(job_id)
        return list(job.runs) if job else []

    def get_job_run_error(self, run_id: str) -> str:
        """Error text for one run (getjobrunerror.go)."""
        run = self._find_run(run_id)
        return getattr(run, "error", "") if run else ""

    def get_job_run_debug_message(self, run_id: str) -> str:
        """Executor-side diagnostic dump for one run
        (getjobrundebugmessage.go — job_run.debug)."""
        run = self._find_run(run_id)
        return getattr(run, "debug", "") if run else ""

    def get_job_run_termination_reason(self, run_id: str) -> str:
        """Why the scheduler ended the run (preemption reason;
        getjobrunschedulerterminationreason.go)."""
        run = self._find_run(run_id)
        return getattr(run, "termination_reason", "") if run else ""

    def _find_run(self, run_id: str):
        if self.lookout is not None:
            return self.lookout.get_run(run_id)
        txn = self.jobdb.read_txn()
        for job in txn.all_jobs():
            for r in job.runs:
                if r.id == run_id:
                    return r
        return None

    def active_job_sets(self) -> list[tuple[str, str]]:
        value = self._value
        seen = {}
        for row in self._raw_rows():
            if value(row, "state") in (
                "queued", "leased", "pending", "running"
            ):
                seen[
                    (value(row, "queue"), value(row, "jobset"))
                ] = True
        return sorted(seen)
