"""Job query API: the Lookout data surface.

The reference materializes a denormalized lookout Postgres schema and
serves a REST API with rich filtering/grouping/aggregation
(/root/reference/internal/lookout/repository/{getjobs,groupjobs}.go and
internal/server/queryapi). Here the same query surface runs over the jobdb
materialization directly (the log is the source of truth either way); the
REST/gRPC transport wraps this object.

Supported: field filters (exact/any-of/prefix), ordering, pagination,
group-by with counts and aggregates — the operations the Lookout UI issues.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..jobdb import JobDb, JobState


@dataclass(frozen=True)
class JobFilter:
    """One predicate, mirroring the reference's model.Filter
    (lookout/model/model.go:8-16 match constants; querybuilder.go:616-650
    operator translation). With is_annotation the field names an
    annotation key instead of a column."""

    field: str  # queue | jobset | state | job_id | priority_class | ...
    value: object = None
    match: str = "exact"  # exact | anyOf | startsWith | contains |
    #  greaterThan | lessThan | greaterThanOrEqualTo | lessThanOrEqualTo |
    #  exists
    is_annotation: bool = False


@dataclass(frozen=True)
class Order:
    field: str = "submitted"  # submitted | job_id | priority | state | ...
    direction: str = "asc"


@dataclass
class JobRow:
    job_id: str
    queue: str
    jobset: str
    state: str
    priority: int
    priority_class: str
    submitted: float
    node: str
    executor: str
    attempts: int
    error: str
    error_category: str
    last_transition: float = 0.0
    runtime_s: float = 0.0  # latest run start -> finish (0 while running)
    run_id: str = ""  # latest run
    annotations: dict = field(default_factory=dict)

    @staticmethod
    def from_job(job) -> "JobRow":
        run = job.latest_run
        runtime = 0.0
        if run is not None and run.started and run.finished:
            runtime = max(0.0, run.finished - run.started)
        return JobRow(
            job_id=job.id,
            queue=job.queue,
            jobset=job.jobset,
            state=job.state.value,
            priority=job.priority,
            priority_class=job.spec.priority_class,
            submitted=job.submitted,
            node=run.node_id if run else "",
            executor=run.executor if run else "",
            attempts=job.num_attempts,
            error=job.error,
            error_category=job.error_category,
            last_transition=max(
                job.submitted,
                run.finished if run else 0.0,
                run.started if run else 0.0,
                run.leased if run else 0.0,
            ),
            runtime_s=runtime,
            run_id=run.id if run else "",
            annotations=dict(job.spec.annotations),
        )

    @staticmethod
    def from_lookout(row) -> "JobRow":
        run = row.latest_run
        runtime = 0.0
        if run is not None and run.started and run.finished:
            runtime = max(0.0, run.finished - run.started)
        return JobRow(
            job_id=row.job_id,
            queue=row.queue,
            jobset=row.jobset,
            state=row.state,
            priority=row.priority,
            priority_class=row.priority_class,
            submitted=row.submitted,
            node=run.node if run else "",
            executor=run.executor if run else "",
            attempts=len(row.runs),
            error=row.error,
            error_category=row.error_category,
            last_transition=row.last_transition,
            runtime_s=runtime,
            run_id=run.run_id if run else "",
            annotations=dict(row.annotations),
        )


def _matches(row: JobRow, f: JobFilter) -> bool:
    if f.is_annotation:
        present = f.field in row.annotations
        if f.match == "exists":
            return present
        if not present:
            return False
        actual = row.annotations[f.field]
    else:
        actual = getattr(row, f.field, None)
        if f.match == "exists":
            return actual not in (None, "")
    if f.match == "exact":
        return actual == f.value
    if f.match == "anyOf":
        return actual in f.value
    if f.match == "startsWith":
        return isinstance(actual, str) and actual.startswith(str(f.value))
    if f.match == "contains":
        return isinstance(actual, str) and str(f.value) in actual
    if f.match == "greaterThan":
        return actual is not None and actual > f.value
    if f.match == "lessThan":
        return actual is not None and actual < f.value
    if f.match == "greaterThanOrEqualTo":
        return actual is not None and actual >= f.value
    if f.match == "lessThanOrEqualTo":
        return actual is not None and actual <= f.value
    raise ValueError(f"unknown match {f.match!r}")


class QueryApi:
    """Query surface over either the live jobdb or the independently
    materialized lookout view (pass `lookout=LookoutStore`): the reference
    serves lookout queries from its own Postgres view, never the scheduler
    DB (internal/lookout/repository)."""

    def __init__(self, jobdb: JobDb | None = None, lookout=None):
        assert jobdb is not None or lookout is not None
        self.jobdb = jobdb
        self.lookout = lookout

    def _rows(self) -> list[JobRow]:
        if self.lookout is not None:
            return [JobRow.from_lookout(r) for r in self.lookout.all_rows()]
        txn = self.jobdb.read_txn()
        return [JobRow.from_job(j) for j in txn.all_jobs()]

    def get_jobs(
        self,
        filters: list[JobFilter] = (),
        order: Order = Order(),
        skip: int = 0,
        take: int = 100,
    ) -> tuple[list[JobRow], int]:
        """Filtered, ordered, paginated rows + total match count."""
        rows = [r for r in self._rows() if all(_matches(r, f) for f in filters)]
        rows.sort(
            key=lambda r: getattr(r, order.field),
            reverse=(order.direction == "desc"),
        )
        return rows[skip : skip + take], len(rows)

    def group_jobs(
        self,
        group_by: str,
        filters: list[JobFilter] = (),
        aggregates: list = (),
        group_by_annotation: bool = False,
        order_by: str = "count",
        direction: str = "desc",
        skip: int = 0,
        take: int = 0,
    ) -> list[dict]:
        """Counts (+ aggregates) per group value (groupjobs.go).

        group_by names a column, or an annotation key with
        group_by_annotation (rows missing the key are excluded, matching
        the reference's implicit exists-filter, querybuilder.go:273).
        Aggregates: legacy strings ("submitted_min", "state_counts", ...)
        or reference-style dicts {"field": col, "type": "min|max|average"}
        (aggregates.go GetAggregatorsForColumn). Groups are ordered by
        order_by ("count", "name", or an aggregate name) and paginated
        when take > 0."""
        groups: dict = {}
        agg_specs = []
        for agg in aggregates:
            if isinstance(agg, dict):
                agg_specs.append((f"{agg['field']}_{agg['type']}",
                                  agg["field"], agg["type"]))
            else:
                agg_specs.append((agg, None, None))
        for row in self._rows():
            if not all(_matches(row, f) for f in filters):
                continue
            if group_by_annotation:
                if group_by not in row.annotations:
                    continue
                key = row.annotations[group_by]
            else:
                key = getattr(row, group_by)
            g = groups.setdefault(
                key, {"name": key, "count": 0, "aggregates": {}}
            )
            g["count"] += 1
            for agg, col, typ in agg_specs:
                if col is not None:
                    val = getattr(row, col, None)
                    if typ == "min":
                        cur = g["aggregates"].get(agg)
                        g["aggregates"][agg] = (
                            val if cur is None else min(cur, val)
                        )
                    elif typ == "max":
                        cur = g["aggregates"].get(agg)
                        g["aggregates"][agg] = (
                            val if cur is None else max(cur, val)
                        )
                    elif typ == "average":
                        bucket = g["aggregates"].setdefault(
                            agg, {"sum": 0.0, "n": 0}
                        )
                        bucket["sum"] += float(val or 0.0)
                        bucket["n"] += 1
                    elif typ == "state_counts":
                        sc = g["aggregates"].setdefault(agg, {})
                        sc[row.state] = sc.get(row.state, 0) + 1
                    else:
                        raise ValueError(f"unknown aggregate type {typ!r}")
                elif agg == "submitted_min":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.submitted if cur is None else min(cur, row.submitted)
                    )
                elif agg == "submitted_max":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.submitted if cur is None else max(cur, row.submitted)
                    )
                elif agg == "state_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    sc[row.state] = sc.get(row.state, 0) + 1
                elif agg == "error_category_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    if row.error_category:
                        sc[row.error_category] = sc.get(row.error_category, 0) + 1
                elif agg == "last_transition_max":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.last_transition
                        if cur is None
                        else max(cur, row.last_transition)
                    )
                elif agg == "runtime_avg":
                    bucket = g["aggregates"].setdefault(agg, {"sum": 0.0, "n": 0})
                    if row.runtime_s:
                        bucket["sum"] += row.runtime_s
                        bucket["n"] += 1
        for g in groups.values():
            for name, v in list(g["aggregates"].items()):
                if isinstance(v, dict) and set(v) == {"sum", "n"}:
                    g["aggregates"][name] = v["sum"] / v["n"] if v["n"] else 0.0
        out = list(groups.values())
        if order_by == "count":
            key = lambda g: g["count"]
        elif order_by == "name":
            key = lambda g: g["name"]
        else:
            key = lambda g: g["aggregates"].get(order_by, 0)
        out.sort(key=key, reverse=(direction == "desc"))
        if skip:
            out = out[skip:]
        if take:
            out = out[:take]
        return out

    def get_job_errors(
        self, filters: list[JobFilter] = (), take: int = 100
    ) -> list[dict]:
        """Error drilldown (lookout repository GetJobError + the UI's error
        surfacing): failed jobs with error text + category + run history."""
        out = []
        for row in self._rows():
            if not row.error:
                continue
            if not all(_matches(row, f) for f in filters):
                continue
            out.append(
                {
                    "job_id": row.job_id,
                    "queue": row.queue,
                    "jobset": row.jobset,
                    "state": row.state,
                    "error": row.error,
                    "error_category": row.error_category,
                    "attempts": row.attempts,
                    "node": row.node,
                }
            )
            if len(out) >= take:
                break
        return out

    def job_details(self, job_id: str) -> dict | None:
        """Job drill-down for the UI: spec + run history + error."""
        if self.lookout is not None:
            row = self.lookout.get(job_id)
            if row is None:
                return None
            return {
                "job_id": row.job_id,
                "queue": row.queue,
                "jobset": row.jobset,
                "state": row.state,
                "priority": row.priority,
                "priority_class": row.priority_class,
                "requests": dict(row.requests),
                "annotations": dict(row.annotations),
                "submitted": row.submitted,
                "error": row.error,
                "error_category": row.error_category,
                "runs": [
                    {
                        "run_id": r.run_id,
                        "executor": r.executor,
                        "node": r.node,
                        "state": r.state,
                        "leased": r.leased,
                        "started": r.started,
                        "finished": r.finished,
                        "error": r.error,
                        "debug": r.debug,
                        "termination_reason": r.termination_reason,
                    }
                    for r in row.runs
                ],
            }
        job = self.jobdb.get(job_id)
        if job is None:
            return None
        return {
            "job_id": job.id,
            "queue": job.queue,
            "jobset": job.jobset,
            "state": job.state.value,
            "priority": job.priority,
            "priority_class": job.spec.priority_class,
            "requests": dict(job.spec.requests),
            "annotations": dict(job.spec.annotations),
            "submitted": job.submitted,
            "error": job.error,
            "error_category": job.error_category,
            "runs": [
                {
                    "run_id": r.id,
                    "executor": r.executor,
                    "node": r.node_id,
                    "state": r.state.value,
                    "leased": r.leased,
                    "started": r.started,
                    "finished": r.finished,
                }
                for r in job.runs
            ],
        }

    def get_job_spec(self, job_id: str):
        job = self.jobdb.get(job_id)
        return job.spec if job else None

    def get_job_runs(self, job_id: str):
        job = self.jobdb.get(job_id)
        return list(job.runs) if job else []

    def get_job_run_error(self, run_id: str) -> str:
        """Error text for one run (getjobrunerror.go)."""
        run = self._find_run(run_id)
        return getattr(run, "error", "") if run else ""

    def get_job_run_debug_message(self, run_id: str) -> str:
        """Executor-side diagnostic dump for one run
        (getjobrundebugmessage.go — job_run.debug)."""
        run = self._find_run(run_id)
        return getattr(run, "debug", "") if run else ""

    def get_job_run_termination_reason(self, run_id: str) -> str:
        """Why the scheduler ended the run (preemption reason;
        getjobrunschedulerterminationreason.go)."""
        run = self._find_run(run_id)
        return getattr(run, "termination_reason", "") if run else ""

    def _find_run(self, run_id: str):
        if self.lookout is not None:
            return self.lookout.get_run(run_id)
        txn = self.jobdb.read_txn()
        for job in txn.all_jobs():
            for r in job.runs:
                if r.id == run_id:
                    return r
        return None

    def active_job_sets(self) -> list[tuple[str, str]]:
        seen = {}
        for row in self._rows():
            if row.state in ("queued", "leased", "pending", "running"):
                seen[(row.queue, row.jobset)] = True
        return sorted(seen)
