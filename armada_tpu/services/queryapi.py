"""Job query API: the Lookout data surface.

The reference materializes a denormalized lookout Postgres schema and
serves a REST API with rich filtering/grouping/aggregation
(/root/reference/internal/lookout/repository/{getjobs,groupjobs}.go and
internal/server/queryapi). Here the same query surface runs over the jobdb
materialization directly (the log is the source of truth either way); the
REST/gRPC transport wraps this object.

Supported: field filters (exact/any-of/prefix), ordering, pagination,
group-by with counts and aggregates — the operations the Lookout UI issues.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..jobdb import JobDb, JobState


@dataclass(frozen=True)
class JobFilter:
    field: str  # queue | jobset | state | job_id | priority_class
    value: object = None
    match: str = "exact"  # exact | anyOf | startsWith


@dataclass(frozen=True)
class Order:
    field: str = "submitted"  # submitted | job_id | priority | state
    direction: str = "asc"


@dataclass
class JobRow:
    job_id: str
    queue: str
    jobset: str
    state: str
    priority: int
    priority_class: str
    submitted: float
    node: str
    executor: str
    attempts: int
    error: str
    error_category: str

    @staticmethod
    def from_job(job) -> "JobRow":
        run = job.latest_run
        return JobRow(
            job_id=job.id,
            queue=job.queue,
            jobset=job.jobset,
            state=job.state.value,
            priority=job.priority,
            priority_class=job.spec.priority_class,
            submitted=job.submitted,
            node=run.node_id if run else "",
            executor=run.executor if run else "",
            attempts=job.num_attempts,
            error=job.error,
            error_category=job.error_category,
        )


def _matches(row: JobRow, f: JobFilter) -> bool:
    actual = getattr(row, f.field, None)
    if f.match == "exact":
        return actual == f.value
    if f.match == "anyOf":
        return actual in f.value
    if f.match == "startsWith":
        return isinstance(actual, str) and actual.startswith(str(f.value))
    raise ValueError(f"unknown match {f.match!r}")


class QueryApi:
    def __init__(self, jobdb: JobDb):
        self.jobdb = jobdb

    def _rows(self) -> list[JobRow]:
        txn = self.jobdb.read_txn()
        return [JobRow.from_job(j) for j in txn.all_jobs()]

    def get_jobs(
        self,
        filters: list[JobFilter] = (),
        order: Order = Order(),
        skip: int = 0,
        take: int = 100,
    ) -> tuple[list[JobRow], int]:
        """Filtered, ordered, paginated rows + total match count."""
        rows = [r for r in self._rows() if all(_matches(r, f) for f in filters)]
        rows.sort(
            key=lambda r: getattr(r, order.field),
            reverse=(order.direction == "desc"),
        )
        return rows[skip : skip + take], len(rows)

    def group_jobs(
        self,
        group_by: str,
        filters: list[JobFilter] = (),
        aggregates: list[str] = (),
    ) -> list[dict]:
        """Counts (+ aggregates) per group value (groupjobs.go)."""
        groups: dict = {}
        for row in self._rows():
            if not all(_matches(row, f) for f in filters):
                continue
            key = getattr(row, group_by)
            g = groups.setdefault(
                key, {"name": key, "count": 0, "aggregates": {}}
            )
            g["count"] += 1
            for agg in aggregates:
                if agg == "submitted_min":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.submitted if cur is None else min(cur, row.submitted)
                    )
                elif agg == "submitted_max":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.submitted if cur is None else max(cur, row.submitted)
                    )
                elif agg == "state_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    sc[row.state] = sc.get(row.state, 0) + 1
        return sorted(groups.values(), key=lambda g: -g["count"])

    def get_job_spec(self, job_id: str):
        job = self.jobdb.get(job_id)
        return job.spec if job else None

    def get_job_runs(self, job_id: str):
        job = self.jobdb.get(job_id)
        return list(job.runs) if job else []

    def active_job_sets(self) -> list[tuple[str, str]]:
        seen = {}
        for row in self._rows():
            if row.state in ("queued", "leased", "pending", "running"):
                seen[(row.queue, row.jobset)] = True
        return sorted(seen)
