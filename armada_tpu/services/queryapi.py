"""Job query API: the Lookout data surface.

The reference materializes a denormalized lookout Postgres schema and
serves a REST API with rich filtering/grouping/aggregation
(/root/reference/internal/lookout/repository/{getjobs,groupjobs}.go and
internal/server/queryapi). Here the same query surface runs over the jobdb
materialization directly (the log is the source of truth either way); the
REST/gRPC transport wraps this object.

Supported: field filters (exact/any-of/prefix), ordering, pagination,
group-by with counts and aggregates — the operations the Lookout UI issues.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..jobdb import JobDb, JobState


@dataclass(frozen=True)
class JobFilter:
    field: str  # queue | jobset | state | job_id | priority_class
    value: object = None
    match: str = "exact"  # exact | anyOf | startsWith


@dataclass(frozen=True)
class Order:
    field: str = "submitted"  # submitted | job_id | priority | state
    direction: str = "asc"


@dataclass
class JobRow:
    job_id: str
    queue: str
    jobset: str
    state: str
    priority: int
    priority_class: str
    submitted: float
    node: str
    executor: str
    attempts: int
    error: str
    error_category: str
    last_transition: float = 0.0
    runtime_s: float = 0.0  # latest run start -> finish (0 while running)

    @staticmethod
    def from_job(job) -> "JobRow":
        run = job.latest_run
        runtime = 0.0
        if run is not None and run.started and run.finished:
            runtime = max(0.0, run.finished - run.started)
        return JobRow(
            job_id=job.id,
            queue=job.queue,
            jobset=job.jobset,
            state=job.state.value,
            priority=job.priority,
            priority_class=job.spec.priority_class,
            submitted=job.submitted,
            node=run.node_id if run else "",
            executor=run.executor if run else "",
            attempts=job.num_attempts,
            error=job.error,
            error_category=job.error_category,
            last_transition=max(
                job.submitted,
                run.finished if run else 0.0,
                run.started if run else 0.0,
                run.leased if run else 0.0,
            ),
            runtime_s=runtime,
        )

    @staticmethod
    def from_lookout(row) -> "JobRow":
        run = row.latest_run
        runtime = 0.0
        if run is not None and run.started and run.finished:
            runtime = max(0.0, run.finished - run.started)
        return JobRow(
            job_id=row.job_id,
            queue=row.queue,
            jobset=row.jobset,
            state=row.state,
            priority=row.priority,
            priority_class=row.priority_class,
            submitted=row.submitted,
            node=run.node if run else "",
            executor=run.executor if run else "",
            attempts=len(row.runs),
            error=row.error,
            error_category=row.error_category,
            last_transition=row.last_transition,
            runtime_s=runtime,
        )


def _matches(row: JobRow, f: JobFilter) -> bool:
    actual = getattr(row, f.field, None)
    if f.match == "exact":
        return actual == f.value
    if f.match == "anyOf":
        return actual in f.value
    if f.match == "startsWith":
        return isinstance(actual, str) and actual.startswith(str(f.value))
    raise ValueError(f"unknown match {f.match!r}")


class QueryApi:
    """Query surface over either the live jobdb or the independently
    materialized lookout view (pass `lookout=LookoutStore`): the reference
    serves lookout queries from its own Postgres view, never the scheduler
    DB (internal/lookout/repository)."""

    def __init__(self, jobdb: JobDb | None = None, lookout=None):
        assert jobdb is not None or lookout is not None
        self.jobdb = jobdb
        self.lookout = lookout

    def _rows(self) -> list[JobRow]:
        if self.lookout is not None:
            return [JobRow.from_lookout(r) for r in self.lookout.all_rows()]
        txn = self.jobdb.read_txn()
        return [JobRow.from_job(j) for j in txn.all_jobs()]

    def get_jobs(
        self,
        filters: list[JobFilter] = (),
        order: Order = Order(),
        skip: int = 0,
        take: int = 100,
    ) -> tuple[list[JobRow], int]:
        """Filtered, ordered, paginated rows + total match count."""
        rows = [r for r in self._rows() if all(_matches(r, f) for f in filters)]
        rows.sort(
            key=lambda r: getattr(r, order.field),
            reverse=(order.direction == "desc"),
        )
        return rows[skip : skip + take], len(rows)

    def group_jobs(
        self,
        group_by: str,
        filters: list[JobFilter] = (),
        aggregates: list[str] = (),
    ) -> list[dict]:
        """Counts (+ aggregates) per group value (groupjobs.go)."""
        groups: dict = {}
        for row in self._rows():
            if not all(_matches(row, f) for f in filters):
                continue
            key = getattr(row, group_by)
            g = groups.setdefault(
                key, {"name": key, "count": 0, "aggregates": {}}
            )
            g["count"] += 1
            for agg in aggregates:
                if agg == "submitted_min":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.submitted if cur is None else min(cur, row.submitted)
                    )
                elif agg == "submitted_max":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.submitted if cur is None else max(cur, row.submitted)
                    )
                elif agg == "state_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    sc[row.state] = sc.get(row.state, 0) + 1
                elif agg == "error_category_counts":
                    sc = g["aggregates"].setdefault(agg, {})
                    if row.error_category:
                        sc[row.error_category] = sc.get(row.error_category, 0) + 1
                elif agg == "last_transition_max":
                    cur = g["aggregates"].get(agg)
                    g["aggregates"][agg] = (
                        row.last_transition
                        if cur is None
                        else max(cur, row.last_transition)
                    )
                elif agg == "runtime_avg":
                    bucket = g["aggregates"].setdefault(agg, {"sum": 0.0, "n": 0})
                    if row.runtime_s:
                        bucket["sum"] += row.runtime_s
                        bucket["n"] += 1
        for g in groups.values():
            ra = g["aggregates"].get("runtime_avg")
            if isinstance(ra, dict):
                g["aggregates"]["runtime_avg"] = (
                    ra["sum"] / ra["n"] if ra["n"] else 0.0
                )
        return sorted(groups.values(), key=lambda g: -g["count"])

    def get_job_errors(
        self, filters: list[JobFilter] = (), take: int = 100
    ) -> list[dict]:
        """Error drilldown (lookout repository GetJobError + the UI's error
        surfacing): failed jobs with error text + category + run history."""
        out = []
        for row in self._rows():
            if not row.error:
                continue
            if not all(_matches(row, f) for f in filters):
                continue
            out.append(
                {
                    "job_id": row.job_id,
                    "queue": row.queue,
                    "jobset": row.jobset,
                    "state": row.state,
                    "error": row.error,
                    "error_category": row.error_category,
                    "attempts": row.attempts,
                    "node": row.node,
                }
            )
            if len(out) >= take:
                break
        return out

    def job_details(self, job_id: str) -> dict | None:
        """Job drill-down for the UI: spec + run history + error."""
        if self.lookout is not None:
            row = self.lookout.get(job_id)
            if row is None:
                return None
            return {
                "job_id": row.job_id,
                "queue": row.queue,
                "jobset": row.jobset,
                "state": row.state,
                "priority": row.priority,
                "priority_class": row.priority_class,
                "requests": dict(row.requests),
                "annotations": dict(row.annotations),
                "submitted": row.submitted,
                "error": row.error,
                "error_category": row.error_category,
                "runs": [
                    {
                        "run_id": r.run_id,
                        "executor": r.executor,
                        "node": r.node,
                        "state": r.state,
                        "leased": r.leased,
                        "started": r.started,
                        "finished": r.finished,
                        "error": r.error,
                    }
                    for r in row.runs
                ],
            }
        job = self.jobdb.get(job_id)
        if job is None:
            return None
        return {
            "job_id": job.id,
            "queue": job.queue,
            "jobset": job.jobset,
            "state": job.state.value,
            "priority": job.priority,
            "priority_class": job.spec.priority_class,
            "requests": dict(job.spec.requests),
            "annotations": dict(job.spec.annotations),
            "submitted": job.submitted,
            "error": job.error,
            "error_category": job.error_category,
            "runs": [
                {
                    "run_id": r.id,
                    "executor": r.executor,
                    "node": r.node_id,
                    "state": r.state.value,
                    "leased": r.leased,
                    "started": r.started,
                    "finished": r.finished,
                }
                for r in job.runs
            ],
        }

    def get_job_spec(self, job_id: str):
        job = self.jobdb.get(job_id)
        return job.spec if job else None

    def get_job_runs(self, job_id: str):
        job = self.jobdb.get(job_id)
        return list(job.runs) if job else []

    def active_job_sets(self) -> list[tuple[str, str]]:
        seen = {}
        for row in self._rows():
            if row.state in ("queued", "leased", "pending", "running"):
                seen[(row.queue, row.jobset)] = True
        return sorted(seen)
