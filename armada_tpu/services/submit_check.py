"""SubmitChecker: answers "could this job ever be scheduled?".

Mirrors /root/reference/internal/scheduler/submitcheck.go:73-289: per-executor
node snapshots refreshed each cycle; a submitted gang is checked against
every executor's empty-cluster state (static feasibility + capacity at the
job's priority), gang-aware; results cached by scheduling key. Rejecting
never-schedulable jobs at submission keeps them out of the queues.

Here the check runs the real snapshot + oracle node-selection on an
empty-of-queued copy of each executor's nodes, so checker semantics can
never drift from scheduler semantics.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass

from ..core.config import SchedulingConfig
from ..core.types import JobSpec, QueueSpec
from ..snapshot.round import build_round_snapshot
from ..solver.reference import ReferenceSolver


@dataclass
class CheckResult:
    schedulable: bool
    reason: str = ""


def feasibility_snapshot(config, pool, nodes, jobs):
    """Empty-of-queued feasibility snapshot for "could this gang EVER
    fit" checks: the jobs alone against an executor's empty nodes. The
    ONE builder both the SubmitChecker and the what-if planner's gang
    injection use (armada_tpu/whatif/planner._injection_feasibility),
    so checker and planner semantics cannot drift."""
    jobs = [j.with_(queue=j.queue or "check") for j in jobs]
    queues = sorted({j.queue for j in jobs})
    snap = build_round_snapshot(
        config, pool, nodes, [QueueSpec(q) for q in queues], [], jobs
    )
    return snap


def static_check(config, pool, nodes, jobs) -> CheckResult:
    """Solve the feasibility snapshot with the oracle; all-or-nothing
    (gang-aware: either every job fits together or the check fails with
    the per-job reasons)."""
    snap = feasibility_snapshot(config, pool, nodes, jobs)
    res = ReferenceSolver(snap).solve()
    if res.scheduled_mask.all():
        return CheckResult(True)
    failed = [
        snap.job_ids[i]
        for i in range(snap.num_jobs)
        if not res.scheduled_mask[i]
    ]
    reasons = {
        res.unschedulable_reason[i]
        for i in range(snap.num_jobs)
        if not res.scheduled_mask[i] and res.unschedulable_reason[i]
    }
    return CheckResult(
        False,
        f"{len(failed)} job(s) unschedulable: "
        f"{'; '.join(sorted(reasons)) or 'no fit'}",
    )


class SubmitChecker:
    def __init__(
        self,
        config: SchedulingConfig,
        scheduler=None,
        cache_size: int = 4096,
        cache_ttl_s: float = 60.0,
    ):
        self.config = config
        self.scheduler = scheduler  # source of executor heartbeats
        self._cache: dict = {}
        self._cache_size = cache_size
        self._cache_ttl = cache_ttl_s
        self._cache_epoch: frozenset = frozenset()

    def _executors(self):
        if self.scheduler is None:
            return {}
        return self.scheduler.executors

    def _cordoned(self) -> frozenset:
        if self.scheduler is None:
            return frozenset()
        return frozenset(getattr(self.scheduler, "cordoned_executors", ()))

    def check(self, jobs: list[JobSpec]) -> CheckResult:
        """Gang-aware: all jobs must fit together on some single executor
        (submitcheck.go:212-289). Cordoned executors take no new work
        and are not feasibility candidates."""
        cordoned = self._cordoned()
        executors = {
            name: hb
            for name, hb in self._executors().items()
            if name not in cordoned
        }
        if not executors:
            # No (uncordoned) clusters known: accept; scheduling will wait
            # (the reference treats an empty nodeDb set the same way, and
            # a fully-cordoned fleet is transient by construction).
            return CheckResult(True)
        key = tuple(
            (
                j.queue,
                tuple(sorted(j.requests.items())),
                tuple(sorted(j.node_selector.items())),
                j.tolerations,
                j.priority_class,
            )
            for j in jobs
        )
        # Cache validity: entries expire on TTL and whenever the fleet
        # epoch changes — the executor set, its node counts, OR the
        # cordon set (the reference refreshes its snapshots every cycle,
        # submitcheck.go:100; a cordon that did not invalidate the cache
        # would keep serving verdicts for capacity that just left the
        # fleet, tests/test_whatif.py::test_submit_checker_cordon_epoch).
        epoch = frozenset(
            (name, len(hb.nodes)) for name, hb in executors.items()
        ) | frozenset(("cordoned", name) for name in sorted(cordoned))
        now = _time.time()
        if epoch != self._cache_epoch:
            self._cache.clear()
            self._cache_epoch = epoch
        hit = self._cache.get(key)
        if hit is not None:
            result, stamp = hit
            if now - stamp <= self._cache_ttl:
                return result
            del self._cache[key]

        reasons = []
        ok = False
        for name, hb in executors.items():
            result = self._check_on_executor(hb, jobs)
            if result.schedulable:
                ok = True
                break
            reasons.append(f"{name}: {result.reason}")
        result = CheckResult(ok, "" if ok else "; ".join(reasons))
        if len(self._cache) >= self._cache_size:
            self._cache.clear()
        self._cache[key] = (result, now)
        return result

    def _check_on_executor(self, hb, jobs: list[JobSpec]) -> CheckResult:
        return static_check(self.config, hb.pool, hb.nodes, jobs)
