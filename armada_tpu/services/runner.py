"""Scheduling runners: synchronous (in-cycle) and asynchronous (background).

The reference's runner seam (internal/scheduler/scheduling/runner/types.go:13,
async.go:33): the sync runner solves inside the cycle; the async runner
overlaps the solve with event I/O by scheduling against a snapshot in a
background thread (state machine Idle -> Running -> ResultReady), and the
cycle loop applies finished results on a later tick. Events derived from a
snapshot are safe to apply late: the ingester ignores transitions for jobs
that went terminal in between (at-least-once, idempotent application).
"""

from __future__ import annotations

import threading

IDLE, RUNNING, READY = "idle", "running", "ready"


class SyncRunner:
    """Solve inline; results available immediately (runner/sync.go)."""

    synchronous = True
    state = IDLE

    def submit(self, work) -> None:
        self._result = work()
        self.state = READY

    def poll(self):
        if self.state == READY:
            self.state = IDLE
            result, self._result = self._result, None
            return result
        return None

    @property
    def idle(self) -> bool:
        return self.state == IDLE


class AsyncRunner:
    """Background-thread solve (runner/async.go). One solve in flight at a
    time; the submitting cycle returns immediately and a later cycle picks
    up the result."""

    synchronous = False

    def __init__(self):
        self._lock = threading.Lock()
        self.state = IDLE
        self._result = None
        self._error: Exception | None = None

    def submit(self, work) -> None:
        with self._lock:
            if self.state != IDLE:
                return  # a solve is already in flight
            self.state = RUNNING

        def run():
            try:
                result = work()
                with self._lock:
                    self._result = result
                    self.state = READY
            except Exception as e:  # surfaced at the next poll
                with self._lock:
                    self._error = e
                    self.state = READY

        threading.Thread(target=run, daemon=True).start()

    def poll(self):
        """Finished result or None; re-raises a failed solve's error."""
        with self._lock:
            if self.state != READY:
                return None
            self.state = IDLE
            result, self._result = self._result, None
            error, self._error = self._error, None
        if error is not None:
            raise error
        return result

    def wait(self, timeout: float = 30.0) -> bool:
        """Test helper: block until the in-flight solve finishes."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if self.state != RUNNING:
                    return True
            time.sleep(0.005)
        return False

    @property
    def idle(self) -> bool:
        with self._lock:
            return self.state == IDLE
