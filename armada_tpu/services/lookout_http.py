"""Lookout: HTTP JSON API + single-page web UI over the job database.

The reference serves a React/MUI UI (internal/lookoutui) against a REST API
(internal/lookout) backed by its own Postgres materialization. Here the
same surface is a JSON-over-HTTP gateway onto the QueryApi/reports (the
grpc-gateway pattern, pkg/api/*.pb.gw.go) plus an embedded single-page UI
(lookout_ui.py): job table with server-side filter/sort/group, job-details
drawer with per-run error/debug/termination drilldown, queue fair-share
view, scheduling report.

  GET /api/jobs?filters=<json>&order=&direction=&skip=&take=
      (filters: [{"field","value","match","isAnnotation"}]; the simple
       queue=/state=/jobset= params still work)
  GET /api/groups?by=F[&byAnnotation=1]&aggregates=<json>&filters=<json>
  GET /api/queues
  GET /api/fairshare             (per-pool queue shares, latest round)
  GET /api/fairness              (fairness observatory: share ledger,
                                  preemption attribution, starvation
                                  alerts — observe/fairness.py)
  GET /api/report
  GET /api/errors
  GET /api/logs/<job_id>?tail=N   (binoculars log fetch, when wired)
  GET /api/runs/<run_id>/error|debug|termination
  GET /api/slo                   (SLO compliance + burn rates)
  GET /api/doctor                (self-healing solve path: ladder
                                  breakers, round rejections +
                                  quarantine bundles, failovers)
  GET /api/jobtrace/<job_id>     (job journey: transitions + reasons)
  GET /api/details/<job_id>      (row + runs incl. debug)
  GET /api/job/<id>              (spec + runs)
  GET /                          (the UI)
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse
from dataclasses import asdict

from .lookout_ui import UI_HTML
from .queryapi import JobFilter, Order


def _parse_filters(params: dict) -> list[JobFilter]:
    """Filters from the JSON `filters` param plus the legacy simple
    params (queue=, state=, jobset=)."""
    filters = []
    raw = params.get("filters")
    if raw:
        for f in json.loads(raw):
            filters.append(
                JobFilter(
                    field=f["field"],
                    value=f.get("value"),
                    match=f.get("match", "exact"),
                    is_annotation=bool(
                        f.get("isAnnotation", f.get("is_annotation", False))
                    ),
                )
            )
    for key in ("queue", "state", "jobset"):
        if params.get(key):
            filters.append(JobFilter(key, params[key]))
    return filters


class LookoutHttpServer:
    def __init__(self, query, scheduler, submit, port: int = 0,
                 bind: str = "127.0.0.1", tls: tuple | None = None,
                 auth=None, authorizer=None, binoculars=None,
                 frontdoor=None):
        self.query = query
        self.scheduler = scheduler
        self.submit = submit
        # Optional log access (services/binoculars.py): the reference UI
        # fetches container logs through the binoculars service.
        self.binoculars = binoculars
        # Optional front door (armada_tpu/frontdoor): /api/frontdoor
        # serves shard lag + per-tenant admitted/shed — the overload
        # runbook's "find the hot tenant" view.
        self.frontdoor = frontdoor
        # Optional auth chain for the mutation endpoints (reads stay
        # open, like the reference's lookout deployment posture).
        self.auth = auth
        self.authorizer = authorizer
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _json(self, obj, code=200):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                params = dict(urllib.parse.parse_qsl(parsed.query))
                try:
                    self._route(parsed, params)
                except Exception as e:  # surface handler errors as 500s
                    self._json({"error": str(e)}, 500)

            def do_POST(self):
                parsed = urllib.parse.urlparse(self.path)
                try:
                    # CSRF defense: cross-origin <form enctype=text/plain>
                    # submissions cannot set custom headers or this
                    # content type; the UI's fetch() sets both.
                    if (
                        self.headers.get("Content-Type", "")
                        .split(";")[0]
                        .strip()
                        != "application/json"
                        or self.headers.get("X-Requested-With")
                        != "armada-lookout"
                    ):
                        self._json(
                            {"error": "missing CSRF headers"}, 403
                        )
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    body = (
                        json.loads(self.rfile.read(length)) if length else {}
                    )
                    self._mutate(parsed.path, body)
                except Exception as e:
                    self._json({"error": str(e)}, 500)

            def _mutate(self, path, body):
                """UI mutations (the reference UI's cancel/reprioritize
                actions, lookoutui submitApi usage)."""
                if outer.submit is None:
                    self._json({"error": "mutations unavailable"}, 503)
                    return
                if outer.auth is not None:
                    # Same chain as the gRPC API: Authorization header ->
                    # principal -> queue-scoped cancel/reprioritize verbs.
                    from .auth import (
                        CANCEL_ANY_JOBS,
                        REPRIORITIZE_ANY_JOBS,
                        AuthError,
                        PermissionDenied,
                    )

                    try:
                        principal = outer.auth.authenticate(
                            {
                                "authorization": self.headers.get(
                                    "Authorization", ""
                                )
                            }
                        )
                        if outer.authorizer is not None:
                            queue = outer.submit.get_queue(
                                body.get("queue", "")
                            )
                            verb, perm = (
                                ("cancel", CANCEL_ANY_JOBS)
                                if path == "/api/cancel"
                                else ("reprioritize", REPRIORITIZE_ANY_JOBS)
                            )
                            outer.authorizer.authorize_queue(
                                principal, verb, queue, perm
                            )
                    except AuthError as e:
                        self._json({"error": str(e)}, 401)
                        return
                    except PermissionDenied as e:
                        self._json({"error": str(e)}, 403)
                        return
                if path == "/api/cancel":
                    queue, jobset = body.get("queue"), body.get("jobset")
                    ids = body.get("job_ids") or []
                    reason = body.get("reason", "cancelled from lookout")
                    if not queue or not jobset:
                        self._json({"error": "queue and jobset required"}, 400)
                        return
                    if ids:
                        for jid in ids:
                            outer.submit.cancel_job(queue, jobset, jid, reason)
                    else:
                        outer.submit.cancel_jobset(queue, jobset, reason)
                    self._json({"cancelled": len(ids) or "jobset"})
                elif path == "/api/reprioritize":
                    for jid in body.get("job_ids") or []:
                        outer.submit.reprioritise_job(
                            body["queue"], body["jobset"], jid,
                            int(body["priority"]),
                        )
                    self._json({"reprioritized": len(body.get("job_ids") or [])})
                else:
                    self._json({"error": "not found"}, 404)

            def _route(self, parsed, params):
                if parsed.path == "/" or parsed.path == "/index.html":
                    body = UI_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parsed.path == "/api/jobs":
                    rows, total = outer.query.get_jobs(
                        _parse_filters(params),
                        Order(
                            params.get("order", "submitted"),
                            params.get("direction", "desc"),
                        ),
                        int(params.get("skip", 0)),
                        int(params.get("take", 100)),
                    )
                    self._json({"jobs": [asdict(r) for r in rows], "total": total})
                elif parsed.path == "/api/groups":
                    aggregates = []
                    if params.get("aggregates"):
                        aggregates = json.loads(params["aggregates"])
                    self._json(
                        {
                            "groups": outer.query.group_jobs(
                                params.get("by", "state"),
                                _parse_filters(params),
                                aggregates=aggregates,
                                group_by_annotation=params.get("byAnnotation")
                                in ("1", "true"),
                                order_by=params.get("orderBy", "count"),
                                direction=params.get("direction", "desc"),
                                skip=int(params.get("skip", 0)),
                                take=int(params.get("take", 0)),
                            )
                        }
                    )
                elif parsed.path == "/api/queues":
                    self._json(
                        {
                            "queues": [
                                {
                                    "name": q.spec.name,
                                    "priority_factor": q.spec.priority_factor,
                                    "cordoned": q.cordoned,
                                }
                                for q in outer.submit.queues.values()
                            ]
                        }
                    )
                elif parsed.path == "/api/fairshare":
                    # Queue oversight: the latest round's per-queue shares
                    # (lookoutui's fair-share/oversight columns; reports
                    # QueueReport per pool).
                    pools = {}
                    for pool, rep in (
                        outer.scheduler.reports.latest_reports().items()
                    ):
                        pools[pool] = [
                            {
                                "queue": qr.queue,
                                "fair_share": qr.fair_share,
                                "adjusted_fair_share": qr.adjusted_fair_share,
                                "actual_share": qr.actual_share,
                                "scheduled_jobs": qr.scheduled_jobs,
                                "preempted_jobs": qr.preempted_jobs,
                                "top_reasons": dict(qr.top_reasons),
                            }
                            for qr in rep.queues.values()
                        ]
                    self._json({"pools": pools})
                elif parsed.path == "/api/fairness":
                    # Fairness observatory (observe/fairness.py): the
                    # latest per-pool share ledger (entitlement vs
                    # delivered, regret, Jain), the round's preemption
                    # attribution map and active starvation alerts —
                    # the "Diagnosing an unfair pool" runbook's first
                    # stop (docs/operations.md).
                    tracker = getattr(outer.scheduler, "fairness", None)
                    if tracker is None:
                        self._json(
                            {"error": "fairness observatory not enabled"},
                            503,
                        )
                        return
                    self._json(tracker.snapshot())
                elif parsed.path == "/api/report":
                    self._json(
                        {"report": outer.scheduler.reports.scheduling_report()}
                    )
                elif parsed.path == "/api/prices":
                    # Market mode: last round's indicative gang prices
                    # (MarketDrivenIndicativePrices surfaced by
                    # cycle_metrics.go:681; spot price per pool).
                    self._json(
                        {
                            pool: {
                                "spot_price": rep.spot_price,
                                "gangs": {
                                    name: asdict(pr)
                                    for name, pr in rep.indicative_prices.items()
                                },
                            }
                            for pool, rep in
                            outer.scheduler.reports.latest_reports().items()
                        }
                    )
                elif parsed.path == "/api/errors":
                    self._json(
                        {"errors": outer.query.get_job_errors(
                            _parse_filters(params)
                        )}
                    )
                elif parsed.path.startswith("/api/runs/"):
                    # /api/runs/<run_id>/<error|debug|termination>
                    parts = parsed.path.split("/")
                    if len(parts) != 5:
                        self._json({"error": "bad run path"}, 404)
                        return
                    run_id, kind = parts[3], parts[4]
                    fn = {
                        "error": outer.query.get_job_run_error,
                        "debug": outer.query.get_job_run_debug_message,
                        "termination":
                            outer.query.get_job_run_termination_reason,
                    }.get(kind)
                    if fn is None:
                        self._json({"error": f"unknown drilldown {kind}"}, 404)
                    else:
                        self._json({"run_id": run_id, "message": fn(run_id)})
                elif parsed.path.startswith("/api/logs/"):
                    if outer.binoculars is None:
                        self._json({"error": "logs unavailable"}, 503)
                        return
                    job_id = parsed.path.rsplit("/", 1)[1]
                    try:
                        tail = int(params.get("tail", 100))
                        # 0 is rejected too: lines[-0:] would mean "all".
                        if tail <= 0:
                            raise ValueError
                    except ValueError:
                        self._json({"error": "tail must be a positive "
                                    "integer"}, 400)
                        return
                    try:
                        lines = outer.binoculars.get_logs(job_id, tail)
                    except KeyError as e:
                        self._json({"error": e.args[0] if e.args else str(e)},
                                   404)
                        return
                    self._json({"job_id": job_id, "lines": lines})
                elif parsed.path == "/api/whatif":
                    # What-if planner (armada_tpu/whatif). Without
                    # params: recent plans + active drain statuses.
                    # With ?queue=Q&gang=N[&cpu=&memory=&gpu=][&solver=]
                    # [&rounds=]: run a gang-injection what-if on the
                    # bounded planner worker (503 on backpressure).
                    svc = getattr(outer.scheduler, "whatif", None)
                    if svc is None:
                        self._json({"error": "what-if planner not "
                                    "enabled"}, 503)
                        return
                    if params.get("queue") and params.get("gang"):
                        from ..whatif import mutations_from_dicts
                        from ..whatif.planner import WhatIfBusyError

                        mutation = {
                            "kind": "inject_gang",
                            "queue": params["queue"],
                            "gang_cardinality": int(params["gang"]),
                        }
                        for key in ("cpu", "memory", "gpu"):
                            if params.get(key):
                                mutation[key] = params[key]
                        try:
                            plan = svc.plan(
                                mutations_from_dicts([mutation]),
                                pool=params.get("pool") or None,
                                solver=params.get("solver") or None,
                                rounds=int(params["rounds"])
                                if params.get("rounds")
                                else None,
                            )
                        except WhatIfBusyError as e:
                            self._json({"error": str(e)}, 503)
                            return
                        self._json(
                            {"plan": plan.to_dict(),
                             "rendered": plan.render()}
                        )
                        return
                    self._json(
                        {
                            "plans": list(svc.recent),
                            "drains": svc.drain_status() or {},
                        }
                    )
                elif parsed.path == "/api/slo":
                    # SLO status (services/slo.py): declared objectives,
                    # compliance and multi-window burn rates — the view
                    # the "Reading the round cost ledger" runbook pairs
                    # with /metrics to decide whether churn is hurting
                    # users yet.
                    tracker = getattr(outer.scheduler, "slo", None)
                    if tracker is None:
                        self._json({"error": "SLO tracking not enabled"},
                                   503)
                        return
                    self._json(tracker.snapshot())
                elif parsed.path == "/api/doctor":
                    # Self-healing solve path (solver/validate.py +
                    # solver/failover.py): ladder breaker states, recent
                    # admission-firewall rejections with quarantine
                    # bundle paths, recent failovers — the "Responding
                    # to a quarantined round" runbook's first stop
                    # (docs/operations.md).
                    report = getattr(
                        outer.scheduler, "doctor_report", None
                    )
                    if report is None:
                        self._json(
                            {"error": "doctor report not available"}, 503
                        )
                        return
                    self._json(report())
                elif parsed.path == "/api/frontdoor":
                    # Front-door overload view (armada_tpu/frontdoor):
                    # per-shard ingest lag / delivery counters and the
                    # per-tenant admitted/shed table sorted hot-first —
                    # the "Surviving an overload" runbook reads this to
                    # identify the tenant to re-quota.
                    if outer.frontdoor is None:
                        self._json({"error": "front door not enabled"},
                                   503)
                        return
                    self._json(outer.frontdoor.snapshot())
                elif parsed.path.startswith("/api/jobtrace/"):
                    # Job journey (services/job_timeline.py): transitions
                    # + aggregated unschedulable-round history + trace id.
                    # Local view, like every lookout read (a follower's
                    # ledger lacks round reasons — the leader runs the
                    # rounds; the gRPC JobTrace method leader-proxies).
                    job_id = parsed.path.rsplit("/", 1)[1]
                    trace = outer.query.job_trace(job_id)
                    if trace is None:
                        self._json({"error": "no journey recorded"}, 404)
                    else:
                        self._json(trace)
                elif parsed.path.startswith("/api/details/"):
                    job_id = parsed.path.rsplit("/", 1)[1]
                    details = outer.query.job_details(job_id)
                    if details is None:
                        self._json({"error": "not found"}, 404)
                    else:
                        self._json(details)
                elif parsed.path.startswith("/api/job/"):
                    job_id = parsed.path.rsplit("/", 1)[1]
                    spec = outer.query.get_job_spec(job_id)
                    if spec is None:
                        self._json({"error": "not found"}, 404)
                    else:
                        self._json(
                            {
                                "spec": asdict(spec),
                                "runs": [
                                    asdict(r)
                                    for r in outer.query.get_job_runs(job_id)
                                ],
                            }
                        )
                else:
                    self._json({"error": "not found"}, 404)

            def log_message(self, *a):
                pass

        # Loopback by default, matching the gRPC API posture; pass
        # bind="0.0.0.0" explicitly to expose on the network.
        self.server = http.server.ThreadingHTTPServer((bind, port), Handler)
        if tls is not None:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls[0], tls[1])
            self.server.socket = ctx.wrap_socket(
                self.server.socket, server_side=True
            )
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.server.shutdown()
