"""Lookout: HTTP JSON API + single-page web UI over the job database.

The reference serves a React/MUI UI (internal/lookoutui) against a REST API
(internal/lookout) backed by its own Postgres materialization. Here the
same surface is a JSON-over-HTTP gateway onto the QueryApi/reports (the
grpc-gateway pattern, pkg/api/*.pb.gw.go) plus an embedded single-page UI:
job table with filtering/grouping, queue overview, scheduling report.

  GET /api/jobs?queue=&state=&skip=&take=
  GET /api/groups?by=state|queue|jobset
  GET /api/queues
  GET /api/report
  GET /api/job/<id>          (spec + runs)
  GET /                      (the UI)
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse
from dataclasses import asdict

from .queryapi import JobFilter, Order

UI_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>armada-tpu lookout</title>
<style>
body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1a1d21}
header{background:#101828;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:baseline}
header h1{font-size:16px;margin:0} header span{color:#98a2b3;font-size:12px}
main{padding:16px 20px;max-width:1200px;margin:auto}
.controls{display:flex;gap:8px;margin-bottom:12px}
input,select,button{padding:6px 8px;border:1px solid #d0d5dd;border-radius:6px;font-size:13px}
button{background:#101828;color:#fff;cursor:pointer}
table{width:100%;border-collapse:collapse;background:#fff;border-radius:8px;overflow:hidden;
box-shadow:0 1px 2px rgba(0,0,0,.06);font-size:13px}
th,td{padding:8px 10px;text-align:left;border-bottom:1px solid #eaecf0}
th{background:#f9fafb;font-weight:600;font-size:12px;color:#475467}
.state{padding:2px 8px;border-radius:10px;font-size:11px;font-weight:600}
.state.queued{background:#eff8ff;color:#175cd3}.state.running{background:#ecfdf3;color:#067647}
.state.leased{background:#fffaeb;color:#b54708}.state.succeeded{background:#f0fdf4;color:#15803d}
.state.failed,.state.preempted{background:#fef3f2;color:#b42318}
.state.cancelled{background:#f2f4f7;color:#475467}
.cards{display:flex;gap:12px;margin-bottom:16px}
.card{background:#fff;border-radius:8px;padding:12px 16px;box-shadow:0 1px 2px rgba(0,0,0,.06)}
.card b{display:block;font-size:20px}.card span{font-size:12px;color:#475467}
pre{background:#fff;padding:12px;border-radius:8px;font-size:12px;overflow:auto}
</style></head><body>
<header><h1>armada-tpu</h1><span>lookout</span></header>
<main>
<div class="cards" id="cards"></div>
<div class="controls">
<input id="q" placeholder="queue filter">
<select id="st"><option value="">any state</option>
<option>queued</option><option>leased</option><option>running</option>
<option>succeeded</option><option>failed</option><option>cancelled</option><option>preempted</option></select>
<button onclick="load()">refresh</button>
<button onclick="toggleReport()">scheduling report</button>
<button onclick="toggleErrors()">errors</button>
</div>
<pre id="report" style="display:none"></pre>
<pre id="errors" style="display:none"></pre>
<div id="details" style="display:none;position:fixed;top:8%;left:50%;transform:translateX(-50%);
background:#fff;border-radius:8px;box-shadow:0 8px 30px rgba(0,0,0,.25);padding:16px;
max-width:700px;max-height:80%;overflow:auto;z-index:10">
<button style="float:right" onclick="hideDetails()">close</button>
<pre id="details-body" style="background:none"></pre></div>
<table id="jobs"><thead><tr>
<th>job</th><th>queue</th><th>jobset</th><th>state</th><th>node</th><th>executor</th>
<th>attempts</th><th>error</th>
</tr></thead><tbody></tbody></table>
</main>
<script>
async function jget(u){const r=await fetch(u);return r.json()}
function esc(x){return String(x??'').replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
async function load(){
  const q=document.getElementById('q').value, st=document.getElementById('st').value;
  const groups=await jget('/api/groups?by=state'+(q?'&queue='+encodeURIComponent(q):''));
  document.getElementById('cards').innerHTML=groups.groups.map(g=>
    `<div class="card"><b>${g.count}</b><span>${esc(g.name)}</span></div>`).join('');
  let u='/api/jobs?take=200';if(q)u+='&queue='+encodeURIComponent(q);if(st)u+='&state='+st;
  const data=await jget(u);
  document.querySelector('#jobs tbody').innerHTML=data.jobs.map(j=>
    `<tr style="cursor:pointer" onclick="showDetails('${esc(j.job_id)}')">
     <td>${esc(j.job_id)}</td><td>${esc(j.queue)}</td><td>${esc(j.jobset)}</td>
     <td><span class="state ${esc(j.state)}">${esc(j.state)}</span></td>
     <td>${esc(j.node)}</td><td>${esc(j.executor)}</td><td>${esc(j.attempts)}</td>
     <td title="${esc(j.error)}">${esc(j.error_category||(j.error?'error':''))}</td></tr>`).join('');
}
async function showDetails(id){
  const d=await jget('/api/details/'+encodeURIComponent(id));
  document.getElementById('details-body').textContent=JSON.stringify(d,null,2);
  document.getElementById('details').style.display='block';
}
function hideDetails(){document.getElementById('details').style.display='none'}
async function toggleReport(){
  const el=document.getElementById('report');
  if(el.style.display==='none'){el.textContent=(await jget('/api/report')).report;el.style.display='block'}
  else el.style.display='none';
}
async function toggleErrors(){
  const el=document.getElementById('errors');
  if(el.style.display==='none'){
    const d=await jget('/api/errors');
    el.textContent=d.errors.map(e=>`${e.job_id} [${e.error_category}] ${e.error}`).join('\\n')||'no errors';
    el.style.display='block'
  } else el.style.display='none';
}
load();setInterval(load,3000);
</script></body></html>
"""


class LookoutHttpServer:
    def __init__(self, query, scheduler, submit, port: int = 0, bind: str = "127.0.0.1"):
        self.query = query
        self.scheduler = scheduler
        self.submit = submit
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _json(self, obj, code=200):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                params = dict(urllib.parse.parse_qsl(parsed.query))
                try:
                    if parsed.path == "/" or parsed.path == "/index.html":
                        body = UI_HTML.encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "text/html")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    elif parsed.path == "/api/jobs":
                        filters = []
                        if params.get("queue"):
                            filters.append(JobFilter("queue", params["queue"]))
                        if params.get("state"):
                            filters.append(JobFilter("state", params["state"]))
                        if params.get("jobset"):
                            filters.append(JobFilter("jobset", params["jobset"]))
                        rows, total = outer.query.get_jobs(
                            filters,
                            Order(
                                params.get("order", "submitted"),
                                params.get("direction", "desc"),
                            ),
                            int(params.get("skip", 0)),
                            int(params.get("take", 100)),
                        )
                        self._json({"jobs": [asdict(r) for r in rows], "total": total})
                    elif parsed.path == "/api/groups":
                        filters = []
                        if params.get("queue"):
                            filters.append(JobFilter("queue", params["queue"]))
                        self._json(
                            {
                                "groups": outer.query.group_jobs(
                                    params.get("by", "state"), filters
                                )
                            }
                        )
                    elif parsed.path == "/api/queues":
                        self._json(
                            {
                                "queues": [
                                    {
                                        "name": q.spec.name,
                                        "priority_factor": q.spec.priority_factor,
                                        "cordoned": q.cordoned,
                                    }
                                    for q in outer.submit.queues.values()
                                ]
                            }
                        )
                    elif parsed.path == "/api/report":
                        self._json(
                            {"report": outer.scheduler.reports.scheduling_report()}
                        )
                    elif parsed.path == "/api/prices":
                        # Market mode: last round's indicative gang prices
                        # (MarketDrivenIndicativePrices surfaced by
                        # cycle_metrics.go:681; spot price per pool).
                        self._json(
                            {
                                pool: {
                                    "spot_price": rep.spot_price,
                                    "gangs": {
                                        name: asdict(pr)
                                        for name, pr in rep.indicative_prices.items()
                                    },
                                }
                                for pool, rep in
                                outer.scheduler.reports.latest_reports().items()
                            }
                        )
                    elif parsed.path == "/api/errors":
                        filters = []
                        if params.get("queue"):
                            filters.append(JobFilter("queue", params["queue"]))
                        self._json(
                            {"errors": outer.query.get_job_errors(filters)}
                        )
                    elif parsed.path.startswith("/api/details/"):
                        job_id = parsed.path.rsplit("/", 1)[1]
                        details = outer.query.job_details(job_id)
                        if details is None:
                            self._json({"error": "not found"}, 404)
                        else:
                            self._json(details)
                    elif parsed.path.startswith("/api/job/"):
                        job_id = parsed.path.rsplit("/", 1)[1]
                        spec = outer.query.get_job_spec(job_id)
                        if spec is None:
                            self._json({"error": "not found"}, 404)
                        else:
                            self._json(
                                {
                                    "spec": asdict(spec),
                                    "runs": [
                                        asdict(r)
                                        for r in outer.query.get_job_runs(job_id)
                                    ],
                                }
                            )
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # surface handler errors as 500s
                    self._json({"error": str(e)}, 500)

            def log_message(self, *a):
                pass

        # Loopback by default, matching the gRPC API posture; pass
        # bind="0.0.0.0" explicitly to expose on the network.
        self.server = http.server.ThreadingHTTPServer((bind, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.server.shutdown()
