"""TCP-level network chaos proxy: sever the wire itself, on a schedule.

PR 1's fault layer (services/chaos.py) injects failures INSIDE processes
— crashes, hangs, slow leases, torn log writes. This module injects them
BETWEEN processes: a ChaosProxy sits on the TCP path an executor agent
(or a follower proxying report RPCs to the leader) uses to reach the API
server, and, driven by the same seeded `FaultPlan`, can:

  network_partition  sever the link: live connections are torn down and
                     the listener goes DOWN for the window (new connects
                     get kernel-clean ECONNREFUSED) — the classic
                     symmetric partition
  network_blackhole  swallow bytes without closing: the far side never
                     answers, so callers hang until their own deadline
  network_delay      add `param` seconds of latency per forwarded chunk
  network_throttle   cap the forwarding byte rate (param scales
                     THROTTLE_BYTES_PER_SEC)
  network_rst        close with SO_LINGER(0) so the peer sees ECONNRESET
                     rather than a clean FIN

The proxy is deliberately dumb about protocols: it forwards opaque
bytes, so gRPC/HTTP2 framing, TLS, and the JSON and protobuf executor
wires all flow through unmodified. Fault windows are evaluated against
the proxy's clock (seconds since start by default; injectable for
tests), so a plan is a reproducible schedule even though the kernel's
TCP timing is not — determinism lives in WHEN the wire breaks, and the
control plane's job is to converge to the same jobdb state regardless of
how the break interleaves with traffic (the fencing + anti-entropy
protocol asserted by tests/test_netchaos.py; the bit-identical soak runs
on the simulator's virtual-clock partitions instead of real sockets).
"""

from __future__ import annotations

import socket
import threading
import time as _time

from .chaos import FaultPlan

# network_throttle byte rate at param=1.0; the generated param in
# (0.1, 0.9) scales it down, so even a heavily throttled lease exchange
# (a few KiB) completes within a cycle rather than timing out.
THROTTLE_BYTES_PER_SEC = 256 * 1024

_CHUNK = 65536


class ChaosProxy:
    """One proxied TCP link (listen -> upstream) under a FaultPlan.

    `name` is the plan target this link matches (conventionally the
    executor name for agent->server links, "leader" for follower->leader
    report proxying); "*" specs match every link.
    """

    def __init__(
        self,
        name: str,
        upstream_host: str,
        upstream_port: int,
        plan: FaultPlan | None = None,
        clock=None,
        listen_port: int = 0,
    ):
        self.name = name
        self.upstream = (upstream_host, upstream_port)
        self.plan = plan
        self._t0 = _time.monotonic()
        # Default clock: seconds since proxy start, the same zero the
        # plan's windows are authored against in live runs.
        self.clock = clock if clock is not None else (
            lambda: _time.monotonic() - self._t0
        )
        self._listen_port = listen_port
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: set[tuple] = set()  # (client_sock, upstream_sock)
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        # Observability counters (read by tests and operators; the
        # scheduler-side fencing metrics live in services/metrics.py).
        self.connections_total = 0
        self.connections_severed = 0
        self.bytes_forwarded = 0
        self.bytes_blackholed = 0
        self.rebind_errors = 0

    # ---- plan queries ----

    def _active(self, kind: str):
        if self.plan is None:
            return None
        return self.plan.active(kind, self.name, self.clock())

    # ---- lifecycle ----

    def start(self) -> int:
        """Bind and serve; returns the listen port."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind(("127.0.0.1", self._listen_port))
        ls.listen(64)
        self._listener = ls
        self._listen_port = ls.getsockname()[1]
        accept = threading.Thread(target=self._accept_loop, daemon=True)
        accept.start()
        reaper = threading.Thread(target=self._reaper_loop, daemon=True)
        reaper.start()
        self._threads += [accept, reaper]
        return self._listen_port

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self._listen_port}"

    def stop(self):
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self._kill_all(rst=False, count=False)
        for t in self._threads:
            t.join(timeout=2.0)

    # ---- connection handling ----

    def _severed_window(self):
        return (
            self._active("network_partition")
            or self._active("network_rst")
        )

    def _accept_loop(self):
        # The listener polls with a short timeout so sever windows are
        # noticed between connections.
        self._listener.settimeout(0.1)
        while not self._stopping.is_set():
            if self._severed_window() is not None:
                # Severed wire: take the LISTENER down for the window, so
                # new connects are refused cleanly by the kernel
                # (ECONNREFUSED). Accepting and instantly closing instead
                # would RST clients mid-connect — real gRPC clients
                # (grpc 1.68 posix engine) have been observed to wedge
                # their reconnect path for minutes after that, which
                # models a client bug, not a partition.
                self._listener.close()
                while (
                    not self._stopping.is_set()
                    and self._severed_window() is not None
                ):
                    self._stopping.wait(0.05)
                if self._stopping.is_set():
                    return
                # Rebind can transiently fail (TIME_WAIT edge, or another
                # process squatting the released ephemeral port): retry
                # rather than letting the exception kill the accept
                # thread and turn a healed partition into a forever-dead
                # proxy. Persistent failure is surfaced via rebind_errors.
                while not self._stopping.is_set():
                    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    try:
                        ls.bind(("127.0.0.1", self._listen_port))
                        ls.listen(64)
                    except OSError:
                        ls.close()
                        self.rebind_errors += 1
                        self._stopping.wait(0.2)
                        continue
                    ls.settimeout(0.1)
                    self._listener = ls
                    break
                if self._stopping.is_set():
                    return
            try:
                client, _ = self._listener.accept()
            except TimeoutError:
                continue
            except OSError:
                if self._stopping.is_set():
                    return  # listener closed by stop()
                continue
            client.settimeout(None)
            self.connections_total += 1
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
                # The connect timeout must NOT linger as an I/O timeout:
                # a blocking recv that times out after 5 idle seconds
                # would sever every quiet connection (a parked gRPC
                # channel between lease exchanges) without any fault
                # window being active.
                up.settimeout(None)
            except OSError:
                self._close(client, rst=False)
                continue
            pair = (client, up)
            with self._lock:
                self._conns.add(pair)
                # Drop joined pump threads so a long-lived proxy doesn't
                # accumulate dead handles.
                self._threads = [t for t in self._threads if t.is_alive()]
            for src, dst in ((client, up), (up, client)):
                t = threading.Thread(
                    target=self._pump, args=(pair, src, dst), daemon=True
                )
                t.start()
                self._threads.append(t)

    def _pump(self, pair, src: socket.socket, dst: socket.socket):
        try:
            while not self._stopping.is_set():
                try:
                    data = src.recv(_CHUNK)
                except OSError:
                    break
                if not data:
                    break
                spec = self._active("network_partition")
                if spec is not None:
                    self._kill_pair(pair, rst=False)
                    break
                if self._active("network_rst") is not None:
                    self._kill_pair(pair, rst=True)
                    break
                if self._active("network_blackhole") is not None:
                    # Swallow silently; the connection stays open so the
                    # caller blocks on its own deadline, like a routing
                    # black hole (no FIN, no RST, no bytes).
                    self.bytes_blackholed += len(data)
                    continue
                delay = self._active("network_delay")
                if delay is not None and delay.param > 0:
                    _time.sleep(min(delay.param, 5.0))
                throttle = self._active("network_throttle")
                if throttle is not None:
                    rate = max(throttle.param, 0.01) * THROTTLE_BYTES_PER_SEC
                    _time.sleep(min(len(data) / rate, 5.0))
                try:
                    dst.sendall(data)
                except OSError:
                    break
                self.bytes_forwarded += len(data)
        finally:
            # Clean teardown (EOF, peer close): not a severed connection.
            self._kill_pair(pair, rst=False, count=False)

    def _reaper_loop(self):
        """Kill LIVE connections the moment a sever/RST window opens — a
        partition must cut idle and in-flight streams (a parked gRPC
        HTTP/2 connection, a mid-lease exchange), not just future bytes."""
        while not self._stopping.is_set():
            if self._active("network_partition") is not None:
                self._kill_all(rst=False)
            elif self._active("network_rst") is not None:
                self._kill_all(rst=True)
            self._stopping.wait(0.05)

    def _kill_all(self, rst: bool, count: bool = True):
        with self._lock:
            pairs = list(self._conns)
        for pair in pairs:
            self._kill_pair(pair, rst=rst, count=count)

    def _kill_pair(self, pair, rst: bool, count: bool = True):
        with self._lock:
            if pair not in self._conns:
                # Already torn down by the other pump / the reaper; close
                # again anyway (idempotent) but don't double-count.
                first_teardown = False
            else:
                self._conns.discard(pair)
                first_teardown = True
        if first_teardown and count:
            self.connections_severed += 1
        for sock in pair:
            self._close(sock, rst=rst)

    @staticmethod
    def _close(sock: socket.socket, rst: bool):
        try:
            if rst:
                # SO_LINGER with zero timeout: close() sends RST, the
                # peer sees ECONNRESET instead of a clean shutdown.
                import struct

                sock.setsockopt(
                    socket.SOL_SOCKET,
                    socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
            sock.close()
        except OSError:
            pass
