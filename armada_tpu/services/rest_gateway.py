"""REST/JSON gateway for the full API surface.

The reference exposes every gRPC service over REST through grpc-gateway
(/root/reference/pkg/api/*.pb.gw.go, wired in internal/server/server.go);
non-gRPC clients (curl, the C++ client library in native/client) use it.
This gateway fronts the same service objects the gRPC ApiServer uses:

  POST /api/v1/queue                   create queue
  PUT  /api/v1/queue/<name>            update queue
  GET  /api/v1/queue/<name>            get queue
  GET  /api/v1/queues                  list queues
  DELETE /api/v1/queue/<name>          delete queue
  POST /api/v1/job/submit              {queue, jobset, jobs: [...]}
  POST /api/v1/job/cancel              {queue, jobset, job_ids|cancel_jobset}
  POST /api/v1/job/reprioritize        {queue, jobset, job_ids, priority}
  GET  /api/v1/jobset/<q>/<js>/events?from=N[&watch=false]
  GET  /api/v1/jobs?queue=&state=...   query rows

Auth: the same chain as the gRPC server — `authorization` header with
Basic or Bearer credentials, mapped through the shared Authorizer.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .auth import AuthError, PermissionDenied
from .grpc_api import job_spec_from_dict
from .queryapi import JobFilter, Order


class RestGateway:
    def __init__(
        self,
        submit,
        scheduler,
        query,
        log,
        port: int = 0,
        auth=None,
        authorizer=None,
        api=None,
        tls: tuple | None = None,
    ):
        self.submit = submit
        self.scheduler = scheduler
        self.query = query
        self.log = log
        self.auth = auth
        self.authorizer = authorizer
        # Reuse the gRPC ApiServer's authorization mapping when given.
        self._api = api
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _json(self, obj, code=200):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                return json.loads(raw.decode()) if raw else {}

            def _gate(self, method: str, req: dict) -> bool:
                if outer.auth is None:
                    return True
                md = {"authorization": self.headers.get("Authorization", "")}
                try:
                    principal = outer.auth.authenticate(md)
                    if outer._api is not None:
                        outer._api._authorize(method, principal, req)
                    return True
                except AuthError as e:
                    self._json({"error": str(e)}, 401)
                except PermissionDenied as e:
                    self._json({"error": str(e)}, 403)
                return False

            def _route(self, verb: str):
                parsed = urllib.parse.urlparse(self.path)
                params = dict(urllib.parse.parse_qsl(parsed.query))
                parts = [p for p in parsed.path.split("/") if p]
                try:
                    outer._dispatch(self, verb, parts, params)
                except (KeyError,) as e:
                    self._json({"error": str(e)}, 404)
                except ValueError as e:
                    self._json({"error": str(e)}, 400)
                except Exception as e:
                    self._json({"error": repr(e)}, 500)

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_PUT(self):
                self._route("PUT")

            def do_DELETE(self):
                self._route("DELETE")

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        if tls is not None:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls[0], tls[1])
            self.server.socket = ctx.wrap_socket(
                self.server.socket, server_side=True
            )
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.server.shutdown()

    # ---- routing ----

    def _dispatch(self, h, verb: str, parts: list[str], params: dict):
        from ..core.types import QueueSpec

        if parts[:2] != ["api", "v1"]:
            return h._json({"error": "not found"}, 404)
        rest = parts[2:]

        if rest == ["queues"] and verb == "GET":
            if not h._gate("ListQueues", {}):
                return
            return h._json(
                {
                    "queues": [
                        {
                            "name": q.spec.name,
                            "priority_factor": q.spec.priority_factor,
                            "cordoned": q.cordoned,
                        }
                        for q in self.submit.queues.values()
                    ]
                }
            )
        if rest and rest[0] == "queue":
            if verb == "POST" and len(rest) == 1:
                body = h._body()
                if not h._gate("CreateQueue", body):
                    return
                self.submit.create_queue(
                    QueueSpec(
                        body["name"], float(body.get("priority_factor", 1.0))
                    ),
                    cordoned=bool(body.get("cordoned", False)),
                )
                return h._json({})
            if len(rest) == 2:
                name = rest[1]
                if verb == "GET":
                    if not h._gate("GetQueue", {"queue": name}):
                        return
                    q = self.submit.get_queue(name)
                    if q is None:
                        return h._json({"error": "not found"}, 404)
                    return h._json(
                        {
                            "name": q.spec.name,
                            "priority_factor": q.spec.priority_factor,
                            "cordoned": q.cordoned,
                        }
                    )
                if verb == "PUT":
                    body = h._body()
                    if not h._gate("UpdateQueue", body):
                        return
                    pf = body.get("priority_factor")
                    self.submit.update_queue(
                        name,
                        priority_factor=float(pf) if pf is not None else None,
                        cordoned=body.get("cordoned"),
                    )
                    return h._json({})
                if verb == "DELETE":
                    if not h._gate("DeleteQueue", {"queue": name}):
                        return
                    self.submit.delete_queue(name)
                    return h._json({})
        if rest == ["job", "submit"] and verb == "POST":
            # Binary protobuf on the same route (proto/armada.proto
            # JobSubmitRequest) — the transcoding the reference's
            # grpc-gateway does for pkg/api/submit.proto. Codegen clients
            # (e.g. the C++ client) POST application/x-protobuf; the
            # json_format mapping lands in the identical body dict.
            ctype = h.headers.get("Content-Type", "")
            if ctype.startswith("application/x-protobuf"):
                from google.protobuf import json_format

                from ..proto import armada_pb2 as pb

                length = int(h.headers.get("Content-Length", 0))
                raw = h.rfile.read(length) if length else b""
                body = json_format.MessageToDict(
                    pb.JobSubmitRequest.FromString(raw),
                    preserving_proto_field_name=True,
                    always_print_fields_with_no_presence=True,
                )
            else:
                body = h._body()
            if not h._gate("SubmitJobs", body):
                return
            jobs = [
                job_spec_from_dict(j).with_(
                    queue=body["queue"], jobset=body["jobset"]
                )
                for j in body.get("jobs", [])
            ]
            ids = self.submit.submit(body["queue"], body["jobset"], jobs)
            if "application/x-protobuf" in h.headers.get("Accept", ""):
                from ..proto import armada_pb2 as pb

                payload = pb.JobSubmitResponse(
                    job_ids=ids
                ).SerializeToString()
                h.send_response(200)
                h.send_header("Content-Type", "application/x-protobuf")
                h.send_header("Content-Length", str(len(payload)))
                h.end_headers()
                h.wfile.write(payload)
                return
            return h._json({"job_ids": ids})
        if rest == ["job", "cancel"] and verb == "POST":
            body = h._body()
            if not h._gate("CancelJobs", body):
                return
            for job_id in body.get("job_ids", []):
                self.submit.cancel_job(
                    body["queue"], body["jobset"], job_id, body.get("reason", "")
                )
            if body.get("cancel_jobset"):
                self.submit.cancel_jobset(
                    body["queue"], body["jobset"], body.get("reason", "")
                )
            return h._json({})
        if rest == ["job", "reprioritize"] and verb == "POST":
            body = h._body()
            if not h._gate("ReprioritizeJobs", body):
                return
            for job_id in body.get("job_ids", []):
                self.submit.reprioritise_job(
                    body["queue"], body["jobset"], job_id, int(body["priority"])
                )
            return h._json({})
        if rest[:1] == ["jobset"] and len(rest) == 4 and rest[3] == "events":
            queue, jobset = rest[1], rest[2]
            if not h._gate("WatchJobSet", {"queue": queue}):
                return
            events = []
            start = int(params.get("from", 0))
            for entry in self.log.read(start, int(params.get("limit", 1000))):
                seq = entry.sequence
                if seq.queue != queue or seq.jobset != jobset:
                    continue
                for event in seq.events:
                    events.append(
                        {
                            "offset": entry.offset,
                            "type": type(event).__name__,
                            "job_id": getattr(event, "job_id", ""),
                            "created": getattr(event, "created", 0.0),
                        }
                    )
            end = self.log.end_offset
            return h._json({"events": events, "next": end})
        if rest == ["jobs"] and verb == "GET":
            if not h._gate("GetJobs", params):
                return
            filters = []
            for field_name in ("queue", "jobset", "state", "job_id"):
                if params.get(field_name):
                    filters.append(JobFilter(field_name, params[field_name]))
            rows, total = self.query.get_jobs(
                filters,
                Order(
                    params.get("order", "submitted"),
                    params.get("direction", "desc"),
                ),
                int(params.get("skip", 0)),
                int(params.get("take", 100)),
            )
            return h._json({"jobs": [asdict(r) for r in rows], "total": total})
        return h._json({"error": "not found"}, 404)
