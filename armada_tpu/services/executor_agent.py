"""Remote executor agent: a separate process speaking the lease protocol.

The reference's executor (internal/executor/application.go) is an agent per
worker cluster: it reports node state, receives leases over the
ExecutorApi stream, creates pods, and reports their lifecycle. This agent
is the same loop over the gRPC ExecutorLease/ReportEvents methods, with a
simulated pod runtime (the fake cluster context) — swap `_PodRuntime` for a
real container backend to manage actual machines.

  python -m armada_tpu.services.executor_agent \
      --server HOST:PORT --name clusterA --nodes 100 --cpu 8 [--pool p]
"""

from __future__ import annotations

import argparse
import time

from .grpc_api import ApiClient
from .podchecks import PodIssueHandler
from .utilisation import UtilisationReporter, node_reports


class _PodRuntime:
    """Simulated pods: timed sleeps, like the reference fake executor."""

    def __init__(self, runtime_s: float = 30.0, startup_s: float = 0.0):
        self.runtime_s = runtime_s
        self.startup_s = startup_s
        self.pods: dict[str, dict] = {}  # run_id -> pod record

    def create(self, lease: dict, now: float):
        self.pods[lease["run_id"]] = {
            **lease,
            "created": now,
            "last_change": now,
            "node": lease.get("node_id", ""),
            "phase": "created",
        }

    def kill(self, run_id: str):
        self.pods.pop(run_id, None)

    def poll(self, now: float) -> list[dict]:
        """Phase transitions since last poll, as ReportEvents items."""
        events = []
        for pod in list(self.pods.values()):
            base = {
                "job_id": pod["job_id"],
                "run_id": pod["run_id"],
                "queue": pod["queue"],
                "jobset": pod["jobset"],
                "created": now,
            }
            if pod["phase"] == "created":
                events.append({"type": "pending", **base})
                pod["phase"] = "pending"
            elif pod["phase"] == "pending" and now >= pod["created"] + self.startup_s:
                events.append({"type": "running", **base})
                pod["phase"] = "running"
                pod["started"] = now
            elif (
                pod["phase"] == "running"
                and now >= pod["started"] + self.runtime_s
            ):
                events.append({"type": "succeeded", **base})
                self.pods.pop(pod["run_id"], None)
        return events


class ExecutorAgent:
    def __init__(
        self,
        client: ApiClient,
        name: str,
        nodes: list[dict],
        pool: str = "default",
        runtime: _PodRuntime | None = None,
    ):
        self.client = client
        self.name = name
        self.pool = pool
        self.nodes = nodes
        self.runtime = runtime or _PodRuntime()
        self.acked: set[str] = set()
        # Pod-issue machinery + utilisation reporting (executor/podchecks,
        # executor/utilisation): stuck pods are actioned into retry/fail
        # reports; node heartbeats carry usage and the non-framework slice.
        self.issue_handler = PodIssueHandler()
        self.utilisation = UtilisationReporter()
        self.non_framework_usage: dict[str, dict] = {}

    def tick(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        self.utilisation.sample(self.runtime.pods)
        reply = self.client._call(
            "ExecutorLease",
            {
                "executor": self.name,
                "pool": self.pool,
                "nodes": node_reports(
                    self.nodes,
                    self.utilisation.by_node(),
                    self.non_framework_usage,
                ),
                "acked_run_ids": sorted(self.acked),
            },
        )
        for lease in reply.get("leases", []):
            if lease["run_id"] not in self.acked:
                from ..utils.compress import decompress_obj

                lease = {**lease, "spec": decompress_obj(lease.get("spec"))}
                # create before ack: a failed create must be re-leased
                self.runtime.create(lease, now)
                self.acked.add(lease["run_id"])
        for cancel in reply.get("cancel_runs", []):
            self.issue_handler.note_kill(cancel["run_id"], now)
            self.runtime.kill(cancel["run_id"])
            self.issue_handler.note_gone(cancel["run_id"])
        events = self.runtime.poll(now)
        # Pod-issue sweep: stuck pods become retryable/fatal run errors
        # (service/pod_issue_handler.go).
        for issue in self.issue_handler.examine(self.runtime.pods, now):
            pod = self.runtime.pods.get(issue["run_id"])
            if pod is None:
                continue
            events.append(
                {
                    "type": "failed",
                    "job_id": pod["job_id"],
                    "run_id": pod["run_id"],
                    "queue": pod["queue"],
                    "jobset": pod["jobset"],
                    "created": now,
                    "error": f"pod issue: {issue['message']}",
                    "retryable": issue["retryable"],
                }
            )
            self.runtime.kill(issue["run_id"])
        # Reconciliation: runs the server believes are live here but the
        # runtime doesn't know (agent restart, lost pod) are reported
        # failed so the scheduler retries them elsewhere (the reference
        # executor's missing-pod reconciliation).
        for run in reply.get("active_runs", []):
            if run["run_id"] not in self.runtime.pods:
                events.append(
                    {
                        "type": "failed",
                        "job_id": run["job_id"],
                        "run_id": run["run_id"],
                        "queue": run["queue"],
                        "jobset": run["jobset"],
                        "created": now,
                        "error": "pod missing on executor (restart or loss)",
                        "retryable": True,
                    }
                )
        if events:
            self.client._call("ReportEvents", {"events": events})
        # Prune acks for pods that no longer exist: completed runs don't
        # need acks (the server only re-sends LEASED runs), and the set
        # must not grow forever.
        self.acked &= set(self.runtime.pods)
        return reply

    def run(self, interval: float = 1.0):
        while True:
            try:
                self.tick()
            except Exception as e:  # control plane hiccup: retry next tick
                print(f"executor {self.name}: tick failed: {e!r}")
            time.sleep(interval)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="armada-tpu-executor")
    ap.add_argument("--server", default="127.0.0.1:50051")
    ap.add_argument("--name", required=True)
    ap.add_argument("--pool", default="default")
    ap.add_argument("--nodes", type=int, default=10)
    ap.add_argument("--cpu", default="8")
    ap.add_argument("--memory", default="128Gi")
    ap.add_argument("--runtime", type=float, default=30.0)
    ap.add_argument("--interval", type=float, default=1.0)
    args = ap.parse_args(argv)
    nodes = [
        {
            "id": f"{args.name}-node-{i:05d}",
            "total_resources": {"cpu": args.cpu, "memory": args.memory},
        }
        for i in range(args.nodes)
    ]
    agent = ExecutorAgent(
        ApiClient(args.server),
        args.name,
        nodes,
        pool=args.pool,
        runtime=_PodRuntime(runtime_s=args.runtime),
    )
    agent.run(args.interval)


if __name__ == "__main__":
    main()
