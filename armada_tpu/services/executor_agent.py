"""Remote executor agent: a separate process speaking the lease protocol.

The reference's executor (internal/executor/application.go) is an agent per
worker cluster: it reports node state, receives leases over the
ExecutorApi stream, creates pods, and reports their lifecycle. This agent
is the same loop over the gRPC ExecutorLease/ReportEvents methods, with a
simulated pod runtime (the fake cluster context) — swap `_PodRuntime` for a
real container backend to manage actual machines.

  python -m armada_tpu.services.executor_agent \
      --server HOST:PORT --name clusterA --nodes 100 --cpu 8 [--pool p]
"""

from __future__ import annotations

import argparse
import time

from .grpc_api import ApiClient
from .podchecks import PodIssueHandler
from .utilisation import UtilisationReporter, node_reports


class ServiceRegistry:
    """Services/ingresses the executor creates alongside pods
    (executor/job/submit.go:110-140: SubmitService/SubmitIngress with an
    owner reference to the pod, so the objects share its lifecycle).
    Both pod runtimes attach one; records die with their owning pod —
    the owner-reference garbage collection analogue."""

    def __init__(self):
        self.services: dict[str, list[dict]] = {}  # run_id -> records
        self.ingresses: dict[str, list[dict]] = {}

    def create_for(self, lease: dict) -> None:
        spec = lease.get("spec") or {}
        run_id = lease["run_id"]
        job_id = lease.get("job_id", "")
        for n, svc in enumerate(spec.get("services") or ()):
            self.services.setdefault(run_id, []).append(
                {
                    "name": f"armada-{job_id}-{n}-{svc.get('type', 'NodePort').lower()}",
                    "owner_run": run_id,
                    "type": svc.get("type", "NodePort"),
                    "ports": list(svc.get("ports", ())),
                }
            )
        for n, ing in enumerate(spec.get("ingresses") or ()):
            self.ingresses.setdefault(run_id, []).append(
                {
                    "name": f"armada-{job_id}-{n}-ingress",
                    "owner_run": run_id,
                    "ports": list(ing.get("ports", ())),
                    "annotations": dict(
                        tuple(kv) for kv in ing.get("annotations", ())
                    ),
                    "tls_enabled": bool(ing.get("tls_enabled", False)),
                }
            )

    def collect(self, run_id: str) -> None:
        """Owner pod gone: its objects are garbage-collected."""
        self.services.pop(run_id, None)
        self.ingresses.pop(run_id, None)


class _PodRuntime:
    """Simulated pods: timed sleeps, like the reference fake executor."""

    def __init__(self, runtime_s: float = 30.0, startup_s: float = 0.0):
        self.runtime_s = runtime_s
        self.startup_s = startup_s
        self.pods: dict[str, dict] = {}  # run_id -> pod record
        self.objects = ServiceRegistry()

    def create(self, lease: dict, now: float):
        self.pods[lease["run_id"]] = {
            **lease,
            "created": now,
            "last_change": now,
            "node": lease.get("node_id", ""),
            "phase": "created",
        }
        self.objects.create_for(lease)

    def _remove(self, run_id: str):
        """The ONLY way a pod record leaves the runtime: owner-referenced
        objects are garbage-collected with it, structurally."""
        pod = self.pods.pop(run_id, None)
        self.objects.collect(run_id)
        return pod

    def kill(self, run_id: str):
        self._remove(run_id)

    def poll(self, now: float) -> list[dict]:
        """Phase transitions since last poll, as ReportEvents items."""
        events = []
        for pod in list(self.pods.values()):
            base = {
                "job_id": pod["job_id"],
                "run_id": pod["run_id"],
                "queue": pod["queue"],
                "jobset": pod["jobset"],
                "created": now,
                # Echo the lease's trace context so the run's lifecycle
                # reports join the job's submit trace.
                "traceparent": pod.get("traceparent", ""),
            }
            if pod["phase"] == "created":
                events.append({"type": "pending", **base})
                pod["phase"] = "pending"
            elif pod["phase"] == "pending" and now >= pod["created"] + self.startup_s:
                events.append({"type": "running", **base})
                pod["phase"] = "running"
                pod["started"] = now
            elif (
                pod["phase"] == "running"
                and now >= pod["started"] + self.runtime_s
            ):
                events.append({"type": "succeeded", **base})
                self._remove(pod["run_id"])
        return events


class SubprocessPodRuntime:
    """REAL pods: each lease becomes an OS process (the cluster-context
    seam proven end-to-end without Kubernetes — submit.go creates pods,
    here Popen creates processes). The job spec's `command` argv runs with
    an address-space rlimit derived from its memory request (resource
    accounting enforced by the kernel, not simulated); empty commands fall
    back to a sleep of `default_runtime_s`. Phases map as
    created -> pending (spawn) -> running -> succeeded/failed(rc), with rc
    and rusage in the failure debug dump."""

    def __init__(self, default_runtime_s: float = 30.0, enforce_rlimits: bool = True):
        self.default_runtime_s = default_runtime_s
        self.enforce_rlimits = enforce_rlimits
        self.pods: dict[str, dict] = {}  # run_id -> pod record
        self.objects = ServiceRegistry()

    def create(self, lease: dict, now: float):
        self.pods[lease["run_id"]] = {
            **lease,
            "created": now,
            "last_change": now,
            "node": lease.get("node_id", ""),
            "phase": "created",
            "proc": None,
            "stderr": None,
        }
        self.objects.create_for(lease)

    def _spawn(self, pod: dict):
        import subprocess

        spec = pod.get("spec") or {}
        argv = list(spec.get("command") or ())
        if not argv:
            argv = ["/bin/sh", "-c", f"sleep {self.default_runtime_s}"]
        limit_bytes = None
        if self.enforce_rlimits:
            mem = (spec.get("requests") or {}).get("memory")
            if mem:
                from ..core.resources import parse_quantity

                limit_bytes = int(parse_quantity(mem))

        if limit_bytes:
            # The memory rlimit is applied by a shell wrapper between fork
            # and the job's exec — NOT preexec_fn: this process is
            # multithreaded (gRPC client threads, task manager, JAX), and
            # running Python between fork and exec is documented
            # deadlock-prone there. `ulimit -v` takes KiB.
            kib = max(1, limit_bytes // 1024)
            import shlex

            # `|| exit 127`: a failed ulimit (hard limit already lower, or
            # a shell without -v) must fail the pod visibly, not exec the
            # job uncapped — matching the old preexec_fn abort semantics.
            argv = [
                "/bin/sh",
                "-c",
                f"ulimit -v {kib} || exit 127; exec "
                + " ".join(shlex.quote(a) for a in argv),
            ]

        # stderr spools to an unnamed temp file, not a PIPE: a chatty job
        # writing past the pipe buffer would block in write(2) forever with
        # nobody draining it. The file is unbounded, kernel-backed, and
        # read only at failure time.
        import tempfile

        stderr = tempfile.TemporaryFile()
        try:
            return subprocess.Popen(
                argv,
                stdout=subprocess.DEVNULL,
                stderr=stderr,
                start_new_session=True,  # kill() takes the process group
            ), stderr
        except OSError:
            stderr.close()
            raise

    def _remove(self, run_id: str):
        """Sole removal path: closes the stderr spool and garbage-collects
        the pod's owner-referenced objects."""
        pod = self.pods.pop(run_id, None)
        self.objects.collect(run_id)
        if pod and pod.get("stderr") is not None:
            pod["stderr"].close()
        return pod

    def kill(self, run_id: str):
        pod = self._remove(run_id)
        if pod and pod.get("proc") is not None:
            import os as _os
            import signal

            try:
                _os.killpg(pod["proc"].pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            pod["proc"].wait()

    def poll(self, now: float) -> list[dict]:
        events = []
        for pod in list(self.pods.values()):
            base = {
                "job_id": pod["job_id"],
                "run_id": pod["run_id"],
                "queue": pod["queue"],
                "jobset": pod["jobset"],
                "created": now,
                "traceparent": pod.get("traceparent", ""),
            }
            if pod["phase"] == "created":
                try:
                    pod["proc"], pod["stderr"] = self._spawn(pod)
                except OSError as e:
                    events.append(
                        {
                            "type": "failed",
                            **base,
                            "error": f"pod create failed: {e}",
                            "retryable": True,
                            "debug": _pod_debug(pod, now),
                        }
                    )
                    self._remove(pod["run_id"])
                    continue
                pod["phase"] = "pending"
                events.append({"type": "pending", **base})
            elif pod["phase"] == "pending":
                pod["phase"] = "running"
                pod["started"] = now
                events.append({"type": "running", **base})
            elif pod["phase"] == "running":
                rc = pod["proc"].poll()
                if rc is None:
                    continue
                if rc == 0:
                    events.append({"type": "succeeded", **base})
                else:
                    stderr = b""
                    f = pod.get("stderr")
                    if f is not None:
                        size = f.seek(0, 2)
                        f.seek(max(0, size - 500))
                        stderr = f.read() or b""
                    events.append(
                        {
                            "type": "failed",
                            **base,
                            "error": (
                                f"process exited rc={rc}: "
                                f"{stderr.decode(errors='replace')}"
                            ),
                            "retryable": True,
                            "debug": _pod_debug({**pod, "rc": rc}, now),
                        }
                    )
                self._remove(pod["run_id"])
        return events


def _pod_debug(pod: dict, now: float) -> str:
    """Human-readable pod state at failure time — the executor-side dump
    the reference compresses into lookout's job_run.debug column."""
    import json as _json

    dump = {
        "phase": pod.get("phase", ""),
        "node": pod.get("node", ""),
        "created": pod.get("created"),
        "started": pod.get("started"),
        "age_s": round(now - pod.get("created", now), 3),
        "last_change_age_s": round(now - pod.get("last_change", now), 3),
    }
    if "rc" in pod:
        dump["rc"] = pod["rc"]
    return _json.dumps(dump, sort_keys=True)


class ExecutorAgent:
    def __init__(
        self,
        client: ApiClient,
        name: str,
        nodes: list[dict],
        pool: str = "default",
        runtime: _PodRuntime | None = None,
        node_info=None,
        fault_plan=None,
        backoff=None,
        lease_ttl_s: float | None = None,
    ):
        self.client = client
        self.name = name
        self.pool = pool
        # Deterministic fault injection (services/chaos.py) + the retry
        # backoff the injected faults are met with in run().
        self.fault_plan = fault_plan
        self.backoff = backoff
        self._crashed = False
        # Partition safety (split-brain model, docs/architecture.md):
        # lease TTL (None = adopt the server-advertised value from the
        # first lease reply; 0 disables), the monotonic fencing token
        # echoed on every exchange, the instant of the last SUCCESSFUL
        # exchange, and the pods flagged as orphan candidates once the
        # lease expired — kept running (the server may not have expired
        # us yet) but reconciled through ExecutorSync before this agent
        # accepts new work.
        self.lease_ttl_s = lease_ttl_s
        self.fence_token = 0
        self.last_exchange_ok: float | None = None
        self.orphan_candidates: set[str] = set()
        self.syncs = 0  # completed anti-entropy syncs (observability)
        # Node classification (executor/node/node_group.go): derive each
        # node's pool (label + reserved suffix) and node type up front so
        # heartbeats carry them.
        from .node_info import NodeInfoService

        self.node_info = node_info or NodeInfoService(cluster_pool=pool)
        self.nodes = self.node_info.decorate(nodes)
        self.runtime = runtime or _PodRuntime()
        self.acked: set[str] = set()
        # Pod-issue machinery + utilisation reporting (executor/podchecks,
        # executor/utilisation): stuck pods are actioned into retry/fail
        # reports; node heartbeats carry usage and the non-framework slice.
        self.issue_handler = PodIssueHandler()
        self.utilisation = UtilisationReporter()
        self.non_framework_usage: dict[str, dict] = {}
        # Runs whose terminal event we already sent but the server still
        # lists as active (its ingest lags the report by a cycle): the
        # reconciliation sweep must not re-report them as missing pods —
        # that would overwrite the real terminal reason.
        self._reported_terminal: set[str] = set()

    def _inject_faults(self, now: float) -> None:
        """Apply the fault plan before the lease exchange; raises to
        simulate the failure (run()'s backoff loop absorbs it)."""
        plan = self.fault_plan
        if plan is None:
            return
        if plan.active("executor_crash", self.name, now) is not None:
            if not self._crashed:
                for run_id in list(self.runtime.pods):
                    self.runtime.kill(run_id)
                self.acked.clear()
                self._reported_terminal.clear()
                self._crashed = True
            raise RuntimeError("executor crashed (injected fault)")
        self._crashed = False
        if plan.active("executor_hang", self.name, now) is not None:
            raise RuntimeError("executor hung (injected fault)")
        if plan.active("network_partition", self.name, now) is not None:
            # Socketless image of the netchaos sever: the exchange fails
            # exactly like a proxied connection torn mid-RPC. Pods keep
            # running — only the wire is gone.
            raise ConnectionError("network partitioned (injected fault)")
        if plan.active("lease_timeout", self.name, now) is not None:
            raise TimeoutError("lease RPC timed out (injected fault)")
        slow = plan.active("lease_slow", self.name, now)
        if slow is not None and slow.param > 0:
            time.sleep(min(slow.param, 5.0))

    def lease_expired(self, now: float) -> bool:
        """True once no lease exchange has completed within lease_ttl:
        this agent must assume the scheduler has (or soon will have)
        reassigned its runs."""
        ttl = self.lease_ttl_s
        if not ttl or self.last_exchange_ok is None:
            return False
        return now - self.last_exchange_ok > ttl

    def mark_orphan_candidates(self) -> None:
        """Lease expired mid-partition: every running pod may already
        have been requeued server-side. They keep running (killing work
        the server may still own would waste it) but are flagged for the
        anti-entropy sync, and no NEW leases are accepted until it
        completes."""
        if not self.orphan_candidates:
            self.orphan_candidates = set(self.runtime.pods)

    def resync(self, now: float) -> dict:
        """Anti-entropy full-state sync (ExecutorSync): report every pod
        actually held, tear down the ones the server classified zombie or
        duplicate, adopt the current fence token. The one way back into
        the lease flow after a fence bump or an expired lease."""
        runs = [
            {
                "run_id": rid,
                "job_id": pod.get("job_id", ""),
                "phase": pod.get("phase", ""),
            }
            for rid, pod in self.runtime.pods.items()
        ]
        reply = self.client._call(
            "ExecutorSync", {"executor": self.name, "runs": runs}
        )
        for kill in reply.get("kill_runs", []):
            self.issue_handler.note_kill(kill["run_id"], now)
            self.runtime.kill(kill["run_id"])
            self.issue_handler.note_gone(kill["run_id"])
        self.fence_token = int(reply.get("fence_token", 0) or 0)
        self.orphan_candidates.clear()
        self.acked &= set(self.runtime.pods)
        # Runs the sync's orphan sweep already failed server-side must
        # not be re-reported by the missing-pod reconciliation below.
        self._reported_terminal |= set(reply.get("orphaned_run_ids", ()))
        self.syncs += 1
        return reply

    def tick(self, now: float | None = None) -> dict:
        """One agent heartbeat, traced: the tick span's context rides the
        lease/report RPC metadata (ApiClient injects `traceparent`), so
        the server can stitch executor exchanges into cross-process
        traces."""
        now = time.time() if now is None else now
        from ..utils.tracing import TRACER

        with TRACER.span("executor.tick", executor=self.name):
            return self._tick(now)

    def _tick(self, now: float) -> dict:
        self._inject_faults(now)
        was_expired = self.lease_expired(now)
        if was_expired:
            self.mark_orphan_candidates()
        self.utilisation.sample(self.runtime.pods)
        lease_req = {
            "executor": self.name,
            "pool": self.pool,
            "nodes": node_reports(
                self.nodes,
                self.utilisation.by_node(),
                self.non_framework_usage,
            ),
            "acked_run_ids": sorted(self.acked),
            "fence_token": self.fence_token,
        }
        from .grpc_api import is_fenced_error

        synced_this_tick = False
        try:
            reply = self.client._call("ExecutorLease", lease_req)
        except Exception as e:
            if not is_fenced_error(e):
                raise
            # The scheduler reassigned our runs while we were gone: run
            # the anti-entropy sync, then retry the exchange once with
            # the fresh token.
            self.resync(now)
            synced_this_tick = True
            was_expired = True  # stale state: defer new leases this tick
            lease_req["fence_token"] = self.fence_token
            reply = self.client._call("ExecutorLease", lease_req)
        if was_expired and not synced_this_tick:
            # Healed before the server expired us (no fence rejection):
            # reconcile anyway — the lease outlived its TTL, so local and
            # server state may have diverged.
            self.resync(now)
        # Monotonic: never step a fresher token (e.g. one just adopted
        # from a sync) back to an older reply's view.
        self.fence_token = max(
            self.fence_token, int(reply.get("fence_token", 0) or 0)
        )
        if self.lease_ttl_s is None:
            self.lease_ttl_s = float(reply.get("lease_ttl_s", 0.0) or 0.0)
        self.last_exchange_ok = now
        # Store backpressure (the reference pauses pod creation while etcd
        # is over capacity, executor/application.go:63-101): defer NEW
        # leases while the server reports the store unhealthy — they stay
        # unacked and are re-sent once it recovers. Running pods continue.
        # An expired lease defers identically: new work waits for the
        # anti-entropy sync to finish and the next clean exchange.
        if reply.get("store_healthy", True) and not was_expired:
            for lease in reply.get("leases", []):
                if lease["run_id"] not in self.acked:
                    from ..utils.compress import decompress_obj

                    lease = {**lease, "spec": decompress_obj(lease.get("spec"))}
                    # create before ack: a failed create must be re-leased
                    self.runtime.create(lease, now)
                    self.acked.add(lease["run_id"])
        for cancel in reply.get("cancel_runs", []):
            self.issue_handler.note_kill(cancel["run_id"], now)
            self.runtime.kill(cancel["run_id"])
            self.issue_handler.note_gone(cancel["run_id"])
        events = self.runtime.poll(now)
        # Pod-issue sweep: stuck pods become retryable/fatal run errors
        # (service/pod_issue_handler.go).
        for issue in self.issue_handler.examine(self.runtime.pods, now):
            pod = self.runtime.pods.get(issue["run_id"])
            if pod is None:
                continue
            events.append(
                {
                    "type": "failed",
                    "job_id": pod["job_id"],
                    "run_id": pod["run_id"],
                    "queue": pod["queue"],
                    "jobset": pod["jobset"],
                    "created": now,
                    "error": f"pod issue: {issue['message']}",
                    "retryable": issue["retryable"],
                    "traceparent": pod.get("traceparent", ""),
                    # Pod-state dump for the lookout debug drilldown
                    # (job_run.debug, getjobrundebugmessage.go).
                    "debug": _pod_debug(pod, now),
                }
            )
            self.runtime.kill(issue["run_id"])
        # Reconciliation: runs the server believes are live here but the
        # runtime doesn't know (agent restart, lost pod) are reported
        # failed so the scheduler retries them elsewhere (the reference
        # executor's missing-pod reconciliation). A run whose pod already
        # produced its terminal event — this tick OR a recent one the
        # server hasn't ingested yet (active_runs lags by a cycle) — must
        # not be re-reported as missing: that would overwrite the real
        # terminal reason.
        reported = {
            e["run_id"] for e in events if e["type"] in ("failed", "succeeded")
        }
        active_ids = {r["run_id"] for r in reply.get("active_runs", [])}
        # Entries leave the set once the server stops listing the run
        # (ingest caught up), so the set stays bounded. This tick's
        # terminal reports join only AFTER ReportEvents succeeds below —
        # a failed send must leave the run eligible for missing-pod
        # reconciliation (the event was lost; reconciliation is the
        # retry path). (A server with an open lease circuit fails the RPC
        # above — a degraded reply can never reach this bookkeeping.)
        self._reported_terminal &= active_ids
        for run in reply.get("active_runs", []):
            if (
                run["run_id"] not in self.runtime.pods
                and run["run_id"] not in reported
                and run["run_id"] not in self._reported_terminal
            ):
                events.append(
                    {
                        "type": "failed",
                        "job_id": run["job_id"],
                        "run_id": run["run_id"],
                        "queue": run["queue"],
                        "jobset": run["jobset"],
                        "created": now,
                        "error": "pod missing on executor (restart or loss)",
                        "retryable": True,
                    }
                )
        if events:
            self.client._call(
                "ReportEvents",
                {
                    "events": events,
                    # Fenced like the lease path: if the scheduler bumped
                    # our fence between the exchange above and this send,
                    # the report fails FAILED_PRECONDITION and the next
                    # tick's sync resolves the runs instead.
                    "executor": self.name,
                    "fence_token": self.fence_token,
                },
            )
            # The send landed: suppress reconciliation for these runs
            # until the server's view catches up.
            self._reported_terminal |= reported
        # Prune acks for pods that no longer exist: completed runs don't
        # need acks (the server only re-sends LEASED runs), and the set
        # must not grow forever.
        self.acked &= set(self.runtime.pods)
        return reply

    def run(self, interval: float = 1.0):
        """The agent loop: retry with exponential backoff + jitter on any
        tick failure (control-plane hiccup, injected fault), reset on the
        first success — transient faults cost one delayed tick, sustained
        ones back off toward the cap instead of hammering the server.

        The backoff's cumulative sleep is budgeted at lease_ttl: a
        retrying exchange must never sleep past the lease it is renewing.
        Once the budget is spent the lease is presumed dead — running
        pods become orphan candidates, new work is refused, and retries
        poll flat so the heal is noticed promptly and resolved through
        the anti-entropy sync."""
        import zlib

        from .chaos import ExponentialBackoff

        # Seeded per executor: a fleet-wide outage must NOT synchronize
        # every agent's retry instants (decorrelated jitter).
        backoff = self.backoff or ExponentialBackoff(
            base_s=max(interval, 0.1),
            cap_s=60.0,
            seed=zlib.crc32(self.name.encode()),
            budget_s=self.lease_ttl_s,
        )
        while True:
            try:
                self.tick()
            except Exception as e:  # control plane hiccup: back off + retry
                if backoff.budget_s is None and self.lease_ttl_s:
                    # TTL adopted from the server after the backoff was
                    # built: arm the budget now.
                    backoff.budget_s = self.lease_ttl_s
                now = time.time()
                if backoff.exhausted or self.lease_expired(now):
                    self.mark_orphan_candidates()
                delay = backoff.next_delay()
                print(
                    f"executor {self.name}: tick failed: {e!r}; "
                    f"retrying in {delay:.1f}s (attempt {backoff.attempt})"
                )
                time.sleep(delay)
                continue
            backoff.reset()
            time.sleep(interval)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="armada-tpu-executor")
    ap.add_argument("--server", default="127.0.0.1:50051")
    ap.add_argument("--name", required=True)
    ap.add_argument("--pool", default="default")
    ap.add_argument("--nodes", type=int, default=10)
    ap.add_argument("--cpu", default="8")
    ap.add_argument("--memory", default="128Gi")
    ap.add_argument("--runtime", type=float, default=30.0)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument(
        "--lease-ttl",
        type=float,
        default=-1.0,
        help="lease TTL seconds: no successful exchange for this long "
        "marks running pods orphan candidates and defers new work until "
        "an anti-entropy sync; -1 adopts the server-advertised value, "
        "0 disables",
    )
    ap.add_argument(
        "--backend",
        choices=["simulated", "subprocess"],
        default="simulated",
        help="pod runtime: timed sleeps, or real OS processes running "
        "each job's command with rlimit enforcement",
    )
    ap.add_argument(
        "--wire",
        choices=["json", "proto"],
        default="json",
        help="lease-exchange encoding: JSON, or the binary protobuf wire "
        "(proto/armada.proto LeaseRequest/LeaseResponse)",
    )
    ap.add_argument("--ca-cert", default="",
                    help="CA bundle: connect with TLS")
    ap.add_argument("--token", default="",
                    help="Bearer token for the server's auth chain")
    args = ap.parse_args(argv)
    nodes = [
        {
            "id": f"{args.name}-node-{i:05d}",
            "total_resources": {"cpu": args.cpu, "memory": args.memory},
        }
        for i in range(args.nodes)
    ]
    runtime = (
        SubprocessPodRuntime(default_runtime_s=args.runtime)
        if args.backend == "subprocess"
        else _PodRuntime(runtime_s=args.runtime)
    )
    if args.wire == "proto":
        from .grpc_api import ProtoExecutorClient as client_cls
    else:
        client_cls = ApiClient
    client = client_cls(
        args.server, token=args.token or None, ca_cert=args.ca_cert or None
    )
    agent = ExecutorAgent(
        client,
        args.name,
        nodes,
        pool=args.pool,
        runtime=runtime,
        lease_ttl_s=None if args.lease_ttl < 0 else args.lease_ttl,
    )
    agent.run(args.interval)


if __name__ == "__main__":
    main()
