"""Event-stream index: the per-jobset materialized view feeding watchers.

The reference's event ingester (/root/reference/internal/eventingester/
{ingester.go,store/eventstore.go:24-46}) converts the firehose into
per-jobset Redis streams with sequence ids and a retention policy, so
`armadactl watch` readers never scan unrelated traffic. Same role here:
an IngestPipeline consumer materializes {(queue, jobset): [offsets]} with
its own cursor, the watch RPC reads only its jobset's offsets, and
retention trims whole jobsets that have gone quiet.

O(log) work happens once in the indexer instead of once per watcher; a
watcher resuming from offset k binary-searches the jobset's offset list
instead of replaying the log from k.
"""

from __future__ import annotations

import bisect
import threading

from ..events.pipeline import IngestPipeline


class EventStreamIndex:
    def __init__(self, log, *, batch_size: int = 1000, checkpoint=None):
        self.log = log
        self._lock = threading.Lock()
        # (queue, jobset) -> sorted list of log offsets holding its events.
        self._streams: dict[tuple, list[int]] = {}
        # (queue, jobset) -> created ts of the jobset's last event, for
        # retention (eventstore retention policy).
        self._last_activity: dict[tuple, float] = {}
        # Log offset below which the index cannot prove completeness for
        # keys it (re-)created after a retention prune: set by prune(),
        # consulted by offsets_from. A key holding offsets from BEFORE the
        # watermark provably survived every prune, so it stays
        # authoritative from zero.
        self._pruned_through = 0
        start_cursor = 0
        if checkpoint is not None:
            # Bounded restart (services/checkpoint.py): seed the index,
            # replay only the suffix.
            start_cursor, state = checkpoint
            start_cursor = state.get("ingest_cursor", start_cursor)
            self._streams.update(state["streams"])
            self._last_activity.update(state["last_activity"])
            self._pruned_through = state["pruned_through"]
        self._pipeline = IngestPipeline(
            log,
            self._convert,
            self._sink,
            batch_size=batch_size,
            start_cursor=max(start_cursor, log.start_offset),
        )
        # Serializes concurrent sync() callers (every watcher thread pumps
        # the view); the sink stays idempotent regardless.
        self._sync_lock = threading.Lock()

    def checkpoint_state(self):
        with self._lock:
            # The index stores OFFSETS into the log; the bodies live in the
            # log itself. The checkpoint cursor must therefore pin
            # compaction at the oldest offset any live stream still
            # references (not the ingest cursor) — prune() drops quiet
            # jobsets after retention, releasing the pin, so compaction
            # trails retention for watched history.
            referenced = [b[0] for b in self._streams.values() if b]
            pin = min([self._pipeline.cursor] + referenced)
            return pin, {
                "streams": {k: list(v) for k, v in self._streams.items()},
                "last_activity": dict(self._last_activity),
                "pruned_through": self._pruned_through,
                # Restore resumes ingest here (the pin above only gates
                # compaction; re-ingesting from it would be wasted work).
                "ingest_cursor": self._pipeline.cursor,
            }

    # ---- pipeline stages ----

    @staticmethod
    def _convert(entries):
        ops: dict[tuple, list[int]] = {}
        activity: dict[tuple, float] = {}
        for entry in entries:
            seq = entry.sequence
            key = (seq.queue, seq.jobset)
            ops.setdefault(key, []).append(entry.offset)
            for event in seq.events:
                created = getattr(event, "created", 0.0)
                if created:
                    activity[key] = max(activity.get(key, 0.0), created)
        return (ops, activity)

    def _sink(self, ops):
        stream_ops, activity = ops
        with self._lock:
            for key, offsets in stream_ops.items():
                bucket = self._streams.setdefault(key, [])
                # Idempotent under at-least-once replay: offsets are
                # monotone per batch, so drop any already-indexed tail.
                start = 0
                if bucket:
                    while (
                        start < len(offsets) and offsets[start] <= bucket[-1]
                    ):
                        start += 1
                bucket.extend(offsets[start:])
            for key, ts in activity.items():
                if ts > self._last_activity.get(key, 0.0):
                    self._last_activity[key] = ts

    # ---- consumer API ----

    def sync(self) -> int:
        with self._sync_lock:
            return self._pipeline.sync()

    @property
    def lag_events(self) -> int:
        return self._pipeline.lag_events

    def offsets_from(self, queue: str, jobset: str, cursor: int, limit: int = 1000):
        """Offsets >= cursor for one jobset (the per-stream read that
        replaces scanning the whole log), or None when the jobset is not in
        the index (never seen, or pruned by retention) — callers must fall
        back to the log scan in that case, because the log may still hold
        the history the index dropped."""
        with self._lock:
            bucket = self._streams.get((queue, jobset))
            if bucket is None:
                return None
            if cursor < self._pruned_through and (
                not bucket or bucket[0] >= self._pruned_through
            ):
                # The key only has post-prune offsets, so it may be a
                # re-created jobset whose earlier history was pruned; the
                # log, not the index, must answer reads from before the
                # watermark. (A genuinely-new jobset pays one log scan
                # until its watcher advances past the watermark.)
                return None
            i = bisect.bisect_left(bucket, cursor)
            return list(bucket[i : i + limit])

    def read_from(self, queue: str, jobset: str, cursor: int, limit: int = 1000):
        """(offset, EventSequence) pairs for one jobset from cursor; None
        when the jobset is unknown to the index (see offsets_from)."""
        offsets = self.offsets_from(queue, jobset, cursor, limit)
        if offsets is None:
            return None
        # Offsets below the log's compaction point can linger when an
        # external compact() outran this index's checkpoint pin (a
        # mis-wired deployment): skip them instead of crashing the stream.
        start = getattr(self.log, "start_offset", 0)
        out = []
        for offset in offsets:
            if offset < start:
                continue
            entries = self.log.read(offset, 1)
            if entries and entries[0].offset == offset:
                out.append((offset, entries[0].sequence))
        return out

    def prune(self, older_than: float) -> int:
        """Drop jobsets whose last event predates `older_than` (the
        reference's per-jobset retention)."""
        with self._lock:
            # Keys with no recorded activity (events without created
            # timestamps, e.g. control-plane settings) age out too — they
            # would otherwise pin log compaction forever.
            stale = [
                key
                for key in self._streams
                if self._last_activity.get(key, 0.0) < older_than
            ]
            for key in stale:
                self._streams.pop(key, None)
                self._last_activity.pop(key, None)
            if stale:
                self._pruned_through = max(
                    self._pruned_through, self._pipeline.cursor
                )
            return len(stale)
