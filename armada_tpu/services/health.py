"""Health checking: startup + liveness surface.

Mirrors /root/reference/internal/common/health/ (startup checker, multi
checker, HTTP handler wired per service at schedulerapp.go:71-75): each
component registers a named checker; the multi-checker aggregates; an
HTTP endpoint exposes /health (liveness) and /health/startup.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class StartupCompleteChecker:
    """Satisfied once the component signals it finished starting
    (health/startup_complete_checker.go)."""

    def __init__(self, name: str = "startup"):
        self.name = name
        self._complete = False

    def mark_complete(self):
        self._complete = True

    def check(self) -> tuple[bool, str]:
        return (True, "started") if self._complete else (False, "starting")


class FuncChecker:
    """Wraps a callable returning (ok, detail)."""

    def __init__(self, name: str, fn):
        self.name = name
        self.fn = fn

    def check(self) -> tuple[bool, str]:
        try:
            result = self.fn()
            if isinstance(result, tuple):
                return bool(result[0]), str(result[1])
            return bool(result), ""
        except Exception as e:  # a crashing checker is unhealthy
            return False, f"checker raised: {e!r}"


class HeartbeatChecker:
    """Healthy while beats keep arriving within the timeout (used for the
    scheduler cycle loop: a wedged cycle turns the service unhealthy)."""

    def __init__(self, name: str, timeout_s: float):
        self.name = name
        self.timeout_s = timeout_s
        self._last = time.monotonic()

    def beat(self):
        self._last = time.monotonic()

    def check(self) -> tuple[bool, str]:
        age = time.monotonic() - self._last
        ok = age <= self.timeout_s
        return ok, f"last beat {age:.1f}s ago (timeout {self.timeout_s}s)"


class BackpressureChecker:
    """Adapts a backpressure monitor (check() -> (healthy, reason); see
    services/backpressure.py — StoreHealthMonitor, RoundDeadlinePressure)
    into a named health checker with the monitor's reason attached.

    advisory=True reports the tripped reason in the /health payload
    WITHOUT failing the aggregate: round-deadline pressure means the
    scheduler is degrading as designed (committing partial rounds, still
    making progress) — failing the liveness probe for it would invite an
    orchestrator restart loop that helps nothing. Intake shedding for
    such signals belongs on the submit gate (CompositeGate), not
    liveness."""

    def __init__(self, name: str, monitor, advisory: bool = False):
        self.name = name
        self.monitor = monitor
        self.advisory = advisory

    def check(self) -> tuple[bool, str]:
        try:
            healthy, reason = self.monitor.check()
        except Exception as e:  # a crashing monitor is unhealthy
            return False, f"monitor raised: {e!r}"
        if not healthy and self.advisory:
            return True, f"advisory (degraded but live): {reason}"
        return bool(healthy), reason or "ok"


class FencedExecutorChecker:
    """Advisory surface for lease fencing (services/grpc_api.py): names
    executors that were fenced — their runs reassigned after a partition
    — and have not yet completed an anti-entropy ExecutorSync. Always
    healthy: a fenced executor is the PROTOCOL working (stale exchanges
    rejected FAILED_PRECONDITION until the sync lands); failing liveness
    for it would restart a perfectly good scheduler. The detail string is
    the operator's cue that a partition healed badly or an agent is not
    running the sync."""

    def __init__(self, scheduler, name: str = "fenced-executors"):
        self.name = name
        self.scheduler = scheduler

    def check(self) -> tuple[bool, str]:
        breached = sorted(getattr(self.scheduler, "fence_breached", ()))
        if not breached:
            return True, "no fenced executors"
        fences = {
            name: self.scheduler.executor_fence(name) for name in breached
        }
        return True, (
            "advisory (degraded but live): executors awaiting "
            f"post-fence sync: {fences}"
        )


class SolverLadderChecker:
    """Advisory surface for the self-healing solve path (solver/
    failover.py): names ladder rungs whose circuit breakers are open or
    half-open, and the count of recent admission-firewall rejections.
    Always healthy: a degraded ladder means the containment is WORKING
    (rounds still land on lower rungs, poisoned rounds are quarantined,
    nothing invalid commits) — restarting the scheduler for it would
    throw away the breaker state that is routing around the fault. The
    detail string is the operator's cue to run `armadactl doctor`."""

    def __init__(self, scheduler, name: str = "solver-ladder"):
        self.name = name
        self.scheduler = scheduler

    def check(self) -> tuple[bool, str]:
        report = getattr(self.scheduler, "doctor_report", None)
        if report is None:
            return True, "no solve ladder on this scheduler"
        doc = report()
        degraded = [
            f"{row['rung']}={row['state']}"
            for row in doc.get("ladder", ())
            if row.get("state") not in ("closed", "disabled")
        ]
        rejections = len(doc.get("rejections") or ())
        if not degraded and not rejections:
            return True, "all solver rungs closed, no recent rejections"
        return True, (
            "advisory (degraded but live): "
            f"rungs [{', '.join(degraded) or 'all closed'}], "
            f"{rejections} recent round rejection(s) — "
            "see `armadactl doctor`"
        )


class MultiChecker:
    """health/multi_checker.go: all registered checkers must pass."""

    def __init__(self, *checkers):
        self.checkers = list(checkers)

    def add(self, checker):
        self.checkers.append(checker)

    def check(self) -> tuple[bool, dict]:
        results = {}
        ok = True
        for checker in self.checkers:
            c_ok, detail = checker.check()
            results[checker.name] = {"ok": c_ok, "detail": detail}
            ok = ok and c_ok
        return ok, results


def serve_health(
    checker: MultiChecker,
    startup: StartupCompleteChecker | None = None,
    port: int = 0,
):
    """HTTP health endpoint: /health (liveness via the multi-checker) and
    /health/startup (the startup checker alone). Returns (server, port);
    server runs on a daemon thread."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/health/startup" and startup is not None:
                ok, detail = startup.check()
                body = {"ok": ok, "detail": detail}
            elif self.path in ("/health", "/healthz"):
                ok, body = checker.check()
                body = {"ok": ok, "checks": body}
            else:
                self.send_response(404)
                self.end_headers()
                return
            data = json.dumps(body).encode()
            self.send_response(200 if body["ok"] else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]
