"""The Lookout single-page UI (served by lookout_http).

The reference ships a React/MUI app (internal/lookoutui/src: jobs table
with a filter/sort/group toolbar, job details sidebar with runs and
error/debug drilldown, job-sets view, and per-queue oversight). This is
the same surface as one dependency-free page: four views (Jobs, Groups,
Queues, Report) over the JSON API, with a server-side filter builder,
column sorting, pagination, grouping with aggregates, a job-details
drawer with per-run drilldowns, and a fair-share view per pool.
"""

UI_HTML = r"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>armada-tpu lookout</title>
<style>
:root{--bg:#f6f7f9;--fg:#1a1d21;--mut:#475467;--line:#eaecf0;--card:#fff;
--hdr:#101828;--acc:#175cd3}
body{font-family:system-ui,sans-serif;margin:0;background:var(--bg);color:var(--fg)}
header{background:var(--hdr);color:#fff;padding:10px 20px;display:flex;gap:18px;
align-items:center}
header h1{font-size:16px;margin:0}header .sub{color:#98a2b3;font-size:12px}
nav{display:flex;gap:4px;margin-left:24px}
nav button{background:none;border:none;color:#98a2b3;padding:6px 12px;font-size:13px;
cursor:pointer;border-radius:6px}
nav button.on{background:#1d2939;color:#fff}
main{padding:16px 20px;max-width:1280px;margin:auto}
.controls{display:flex;gap:8px;margin-bottom:10px;flex-wrap:wrap;align-items:center}
input,select,button{padding:6px 8px;border:1px solid #d0d5dd;border-radius:6px;
font-size:13px;background:#fff}
button.pri{background:var(--hdr);color:#fff;cursor:pointer;border-color:var(--hdr)}
button.lnk{border:none;background:none;color:var(--acc);cursor:pointer;padding:2px 4px}
.chip{display:inline-flex;gap:6px;align-items:center;background:#eef2f6;
border-radius:12px;padding:3px 10px;font-size:12px}
.chip b{font-weight:600}.chip span{cursor:pointer;color:#667085}
table{width:100%;border-collapse:collapse;background:var(--card);border-radius:8px;
overflow:hidden;box-shadow:0 1px 2px rgba(0,0,0,.06);font-size:13px}
th,td{padding:7px 10px;text-align:left;border-bottom:1px solid var(--line);
white-space:nowrap;overflow:hidden;text-overflow:ellipsis;max-width:220px}
th{background:#f9fafb;font-weight:600;font-size:12px;color:var(--mut);cursor:pointer;
user-select:none}
th .dir{color:var(--acc)}
tr.row:hover{background:#f4f7fb;cursor:pointer}
.state{padding:2px 8px;border-radius:10px;font-size:11px;font-weight:600}
.state.queued{background:#eff8ff;color:#175cd3}.state.running{background:#ecfdf3;color:#067647}
.state.leased,.state.pending{background:#fffaeb;color:#b54708}
.state.succeeded{background:#f0fdf4;color:#15803d}
.state.failed,.state.preempted{background:#fef3f2;color:#b42318}
.state.cancelled{background:#f2f4f7;color:#475467}
.cards{display:flex;gap:12px;margin-bottom:14px;flex-wrap:wrap}
.card{background:var(--card);border-radius:8px;padding:10px 16px;
box-shadow:0 1px 2px rgba(0,0,0,.06);cursor:pointer;min-width:84px}
.card b{display:block;font-size:20px}.card span{font-size:12px;color:var(--mut)}
.card.on{outline:2px solid var(--acc)}
pre{background:var(--card);padding:12px;border-radius:8px;font-size:12px;overflow:auto}
#drawer{position:fixed;top:0;right:-560px;width:540px;height:100%;background:#fff;
box-shadow:-6px 0 30px rgba(0,0,0,.18);transition:right .15s;z-index:20;
overflow:auto;padding:16px}
#drawer.open{right:0}
#drawer h2{font-size:15px;margin:4px 0 10px}
#drawer table{box-shadow:none}
.kv{display:grid;grid-template-columns:140px 1fr;gap:4px 10px;font-size:13px;
margin-bottom:10px}
.kv div:nth-child(odd){color:var(--mut)}
.bar{height:8px;border-radius:4px;background:#e4e7ec;position:relative;min-width:120px}
.bar i{position:absolute;left:0;top:0;bottom:0;border-radius:4px;background:#84caff}
.bar i.actual{background:var(--acc);opacity:.85}
.pager{display:flex;gap:8px;align-items:center;margin-top:10px;font-size:13px;
color:var(--mut)}
.err{color:#b42318;font-size:13px;margin:8px 0}
</style></head><body>
<header><h1>armada-tpu</h1><span class="sub">lookout</span>
<nav>
<button id="tab-jobs" class="on" onclick="show('jobs')">Jobs</button>
<button id="tab-groups" onclick="show('groups')">Groups</button>
<button id="tab-jobsets" onclick="show('jobsets')">Job Sets</button>
<button id="tab-errors" onclick="show('errors')">Errors</button>
<button id="tab-queues" onclick="show('queues')">Queues</button>
<button id="tab-report" onclick="show('report')">Report</button>
</nav>
<span style="flex:1"></span>
<label style="color:#98a2b3;font-size:12px"><input type="checkbox" id="auto" checked>
auto-refresh</label>
</header>
<main>
<div id="v-jobs">
  <div class="cards" id="cards"></div>
  <div class="controls">
    <select id="f-field"><option>queue</option><option>jobset</option>
      <option>job_id</option><option>state</option><option>priority_class</option>
      <option>node</option><option>executor</option><option>error_category</option>
      <option value="__ann__">annotation…</option></select>
    <input id="f-ann" placeholder="annotation key" style="display:none;width:120px">
    <select id="f-match"><option>exact</option><option>startsWith</option>
      <option>contains</option><option>anyOf</option><option>exists</option>
      <option>greaterThan</option><option>lessThan</option></select>
    <input id="f-value" placeholder="value">
    <button class="pri" onclick="addFilter()">add filter</button>
    <span id="chips"></span>
  </div>
  <div class="err" id="jobs-err" style="display:none"></div>
  <table id="jobs"><thead><tr>
    <th data-col="job_id">job</th><th data-col="queue">queue</th>
    <th data-col="jobset">jobset</th><th data-col="state">state</th>
    <th data-col="priority">prio</th><th data-col="node">node</th>
    <th data-col="executor">executor</th><th data-col="attempts">att</th>
    <th data-col="submitted">submitted</th><th data-col="error_category">error</th>
  </tr></thead><tbody></tbody></table>
  <div class="pager">
    <button onclick="page(-1)">&#8592; prev</button>
    <span id="pageinfo"></span>
    <button onclick="page(1)">next &#8594;</button>
    <select id="take" onchange="st.skip=0;load()">
      <option>25</option><option selected>50</option><option>100</option>
      <option>200</option></select>
  </div>
</div>
<div id="v-groups" style="display:none">
  <div class="controls">
    group by
    <select id="g-by"><option>queue</option><option>jobset</option>
      <option>state</option><option>priority_class</option>
      <option>error_category</option><option value="__ann__">annotation…</option>
    </select>
    <input id="g-ann" placeholder="annotation key" style="display:none;width:120px">
    <label><input type="checkbox" id="g-states" checked> state counts</label>
    <label><input type="checkbox" id="g-sub"> submitted min/max</label>
    <label><input type="checkbox" id="g-rt"> runtime avg</label>
    <button class="pri" onclick="loadGroups()">group</button>
  </div>
  <table id="groups"><thead></thead><tbody></tbody></table>
</div>
<div id="v-jobsets" style="display:none">
  <div class="controls">
    queue <input id="js-queue" placeholder="(all queues)" style="width:160px">
    <button class="pri" onclick="loadJobsets()">refresh</button>
  </div>
  <table id="jobsets"><thead><tr><th>queue</th><th>jobset</th><th>jobs</th>
    <th>states</th><th>first submit</th><th>last submit</th><th></th>
  </tr></thead><tbody></tbody></table>
</div>
<div id="v-errors" style="display:none">
  <div class="err" id="errors-err" style="display:none"></div>
  <table id="errors"><thead><tr><th>job</th><th>queue</th><th>jobset</th>
    <th>category</th><th>error</th></tr></thead><tbody></tbody></table>
</div>
<div id="v-queues">
  <div id="fairshare"></div>
</div>
<div id="v-report" style="display:none">
  <pre id="report"></pre>
  <pre id="prices" style="display:none"></pre>
</div>
</main>
<div id="drawer">
  <button style="float:right" onclick="closeDrawer()">close</button>
  <h2 id="d-title"></h2>
  <div id="d-actions" style="margin-bottom:8px"></div>
  <div class="kv" id="d-kv"></div>
  <h2>runs</h2>
  <table id="d-runs"><thead><tr><th>run</th><th>node</th><th>state</th>
    <th>drill</th></tr></thead><tbody></tbody></table>
  <h2>spec</h2>
  <pre id="d-spec"></pre>
  <pre id="d-drill" style="display:none"></pre>
</div>
<script>
const st={view:'jobs',filters:[],order:'submitted',dir:'desc',skip:0,state:''};
async function jget(u){const r=await fetch(u);if(!r.ok)throw new Error(
  (await r.json().catch(()=>({}))).error||r.statusText);return r.json()}
function esc(x){return String(x??'').replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function show(v){st.view=v;
  for(const t of ['jobs','groups','jobsets','errors','queues','report']){
    document.getElementById('v-'+t).style.display=t===v?'':'none';
    document.getElementById('tab-'+t).classList.toggle('on',t===v)}
  refresh()}
document.getElementById('f-field').onchange=e=>{
  document.getElementById('f-ann').style.display=
    e.target.value==='__ann__'?'':'none'};
document.getElementById('g-by').onchange=e=>{
  document.getElementById('g-ann').style.display=
    e.target.value==='__ann__'?'':'none'};
function addFilter(){
  let field=document.getElementById('f-field').value,ann=false;
  if(field==='__ann__'){field=document.getElementById('f-ann').value;ann=true}
  const match=document.getElementById('f-match').value;
  let value=document.getElementById('f-value').value;
  if(match==='anyOf')value=value.split(',').map(s=>s.trim());
  if(match==='greaterThan'||match==='lessThan')value=parseFloat(value);
  if(!field)return;
  st.filters.push({field,value,match,isAnnotation:ann});st.skip=0;load()}
function delFilter(i){st.filters.splice(i,1);st.skip=0;load()}
function renderChips(){
  document.getElementById('chips').innerHTML=st.filters.map((f,i)=>
    `<span class="chip"><b>${esc(f.field)}</b> ${esc(f.match)}
     ${esc(Array.isArray(f.value)?f.value.join(','):f.value??'')}
     <span onclick="delFilter(${i})">&#10005;</span></span>`).join(' ')}
function filtersParam(){
  const fs=[...st.filters];
  if(st.state)fs.push({field:'state',value:st.state,match:'exact'});
  return fs.length?'&filters='+encodeURIComponent(JSON.stringify(fs)):''}
function sortBy(col){
  if(st.order===col)st.dir=st.dir==='asc'?'desc':'asc';
  else{st.order=col;st.dir='asc'}st.skip=0;load()}
document.querySelectorAll('#jobs th').forEach(th=>
  th.onclick=()=>sortBy(th.dataset.col));
function page(d){
  const take=+document.getElementById('take').value;
  st.skip=Math.max(0,st.skip+d*take);load()}
async function load(){
  renderChips();
  const take=+document.getElementById('take').value;
  const err=document.getElementById('jobs-err');err.style.display='none';
  try{
    const groups=await jget('/api/groups?by=state'+filtersParamNoState());
    const total=groups.groups.reduce((a,g)=>a+g.count,0);
    const cards=document.getElementById('cards');
    cards.innerHTML=
      `<div class="card ${st.state?'':'on'}" data-state="">
       <b>${total}</b><span>all</span></div>`+
      groups.groups.map(g=>
      `<div class="card ${st.state===g.name?'on':''}" data-state="${esc(g.name)}">
       <b>${g.count}</b><span>${esc(g.name)}</span></div>`).join('');
    cards.querySelectorAll('.card').forEach(c=>
      c.onclick=()=>{st.state=c.dataset.state;st.skip=0;load()});
    const u=`/api/jobs?take=${take}&skip=${st.skip}&order=${st.order}`+
      `&direction=${st.dir}`+filtersParam();
    const data=await jget(u);
    document.querySelector('#jobs tbody').innerHTML=data.jobs.map(j=>
      `<tr class="row">
       <td>${esc(j.job_id)}</td><td>${esc(j.queue)}</td><td>${esc(j.jobset)}</td>
       <td><span class="state ${esc(j.state)}">${esc(j.state)}</span></td>
       <td>${esc(j.priority)}</td><td>${esc(j.node)}</td>
       <td>${esc(j.executor)}</td><td>${esc(j.attempts)}</td>
       <td>${new Date(j.submitted*1000).toISOString().slice(0,19)}</td>
       <td title="${esc(j.error)}">${esc(j.error_category||(j.error?'error':''))}
       </td></tr>`).join('');
    document.querySelectorAll('#jobs tbody tr').forEach((tr,i)=>
      tr.onclick=()=>openJob(data.jobs[i].job_id));
    document.getElementById('pageinfo').textContent=
      `${st.skip+1}-${Math.min(st.skip+take,data.total)} of ${data.total}`;
  }catch(e){err.textContent=e.message;err.style.display=''}
}
function filtersParamNoState(){
  return st.filters.length?
    '&filters='+encodeURIComponent(JSON.stringify(st.filters)):''}
async function loadGroups(){
  let by=document.getElementById('g-by').value,ann=false;
  if(by==='__ann__'){by=document.getElementById('g-ann').value;ann=true}
  const aggs=[];
  if(document.getElementById('g-sub').checked)
    aggs.push({field:'submitted',type:'min'},{field:'submitted',type:'max'});
  if(document.getElementById('g-rt').checked)
    aggs.push({field:'runtime_s',type:'average'});
  if(document.getElementById('g-states').checked)aggs.push('state_counts');
  const u=`/api/groups?by=${encodeURIComponent(by)}`+(ann?'&byAnnotation=1':'')+
    `&aggregates=${encodeURIComponent(JSON.stringify(aggs))}`+
    filtersParamNoState();
  const data=await jget(u);
  const cols=new Set();
  data.groups.forEach(g=>Object.keys(g.aggregates).forEach(k=>cols.add(k)));
  const cl=[...cols];
  document.querySelector('#groups thead').innerHTML=
    '<tr><th>'+esc(by)+'</th><th>count</th>'+
    cl.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>';
  document.querySelector('#groups tbody').innerHTML=data.groups.map(g=>
    `<tr class="row">
     <td>${esc(g.name)}</td><td>${g.count}</td>`+
    cl.map(c=>{let v=g.aggregates[c];
      if(typeof v==='object'&&v)v=Object.entries(v).map(
        ([k,n])=>`${k}:${n}`).join(' ');
      if(typeof v==='number'&&!Number.isInteger(v))v=v.toFixed(2);
      return '<td>'+esc(v??'')+'</td>'}).join('')+'</tr>').join('');
  document.querySelectorAll('#groups tbody tr').forEach((tr,i)=>
    tr.onclick=()=>drillGroup(by,data.groups[i].name,ann));
}
function drillGroup(field,value,ann){
  st.filters=[{field,value,match:'exact',isAnnotation:!!ann}];st.skip=0;
  show('jobs')}
async function loadQueues(){
  const data=await jget('/api/fairshare');
  let html='';
  for(const pool in data.pools){
    const rows=data.pools[pool];
    html+=`<h3 style="margin:6px 0">pool: ${esc(pool)}</h3>
    <table><thead><tr><th>queue</th><th>fair share</th><th>adjusted</th>
    <th>actual</th><th>share</th><th>scheduled</th><th>preempted</th>
    <th>top reasons</th></tr></thead><tbody>`+
    rows.map(r=>{
      const fs=(r.adjusted_fair_share*100),ac=(r.actual_share*100);
      return `<tr><td>${esc(r.queue)}</td>
      <td>${(r.fair_share*100).toFixed(1)}%</td>
      <td>${fs.toFixed(1)}%</td><td>${ac.toFixed(1)}%</td>
      <td><div class="bar"><i style="width:${Math.min(100,fs)}%"></i>
      <i class="actual" style="width:${Math.min(100,ac)}%"></i></div></td>
      <td>${r.scheduled_jobs}</td><td>${r.preempted_jobs}</td>
      <td>${esc(Object.entries(r.top_reasons||{}).slice(0,3)
        .map(([k,v])=>`${k} (${v})`).join('; '))}</td></tr>`}).join('')+
    '</tbody></table>';
  }
  document.getElementById('fairshare').innerHTML=
    html||'<p style="color:#475467">no scheduling rounds yet</p>';
}
async function loadReport(){
  document.getElementById('report').textContent=
    (await jget('/api/report')).report||'no report yet';
  try{
    const p=await jget('/api/prices');
    if(Object.keys(p).length){
      const el=document.getElementById('prices');
      el.textContent='market prices\n'+JSON.stringify(p,null,2);
      el.style.display=''}
  }catch(e){}
}
async function openJob(id){
  const d=await jget('/api/details/'+encodeURIComponent(id));
  document.getElementById('d-title').textContent=d.job_id;
  const kv=[['queue',d.queue],['jobset',d.jobset],['state',d.state],
    ['priority',d.priority],['priority class',d.priority_class],
    ['submitted',new Date(d.submitted*1000).toISOString()],
    ['error',d.error||''],['error category',d.error_category||'']];
  document.getElementById('d-kv').innerHTML=
    kv.map(([k,v])=>`<div>${esc(k)}</div><div>${esc(v)}</div>`).join('');
  document.querySelector('#d-runs tbody').innerHTML=(d.runs||[]).map(r=>
    `<tr><td title="${esc(r.run_id)}">${esc(r.run_id.slice(0,13))}</td>
     <td>${esc(r.node)}</td>
     <td><span class="state ${esc(r.state)}">${esc(r.state)}</span></td>
     <td><button class="lnk" data-k="error">err</button>
     <button class="lnk" data-k="debug">debug</button>
     <button class="lnk" data-k="termination">term</button>
     </td></tr>`).join('');
  document.querySelectorAll('#d-runs tbody tr').forEach((tr,i)=>
    tr.querySelectorAll('button').forEach(b=>
      b.onclick=()=>drillRun(d.runs[i].run_id,b.dataset.k)));
  document.getElementById('d-spec').textContent=
    JSON.stringify({requests:d.requests,annotations:d.annotations},null,2);
  document.getElementById('d-drill').style.display='none';
  const act=document.getElementById('d-actions');act.innerHTML='';
  {const l=document.createElement('button');l.textContent='logs';
   l.onclick=async()=>{const el=document.getElementById('d-drill');
     try{const data=await jget('/api/logs/'+encodeURIComponent(d.job_id)+
       '?tail=200');
       el.textContent='logs for '+d.job_id+'\n\n'+
         ((data.lines||[]).join('\n')||'(empty)');}
     catch(e){el.textContent='logs: '+e.message}
     el.style.display=''};
   act.append(l,' ');}
  if(['queued','leased','pending','running'].includes(d.state)){
    const c=document.createElement('button');c.className='pri';
    c.textContent='cancel';
    c.onclick=()=>cancelJob(d.queue,d.jobset,d.job_id);
    const r=document.createElement('button');r.textContent='reprioritize';
    r.onclick=()=>reprioritizeJob(d.queue,d.jobset,d.job_id);
    act.append(c,' ',r);
  }
  document.getElementById('drawer').classList.add('open');
}
async function drillRun(runId,kind){
  const d=await jget(`/api/runs/${encodeURIComponent(runId)}/${kind}`);
  const el=document.getElementById('d-drill');
  el.textContent=`${kind} for ${runId}\n\n`+(d.message||'(empty)');
  el.style.display='';
}
function closeDrawer(){document.getElementById('drawer').classList.remove('open')}
async function post(u,body){const r=await fetch(u,{method:'POST',
  headers:{'Content-Type':'application/json',
           'X-Requested-With':'armada-lookout'},body:JSON.stringify(body)});
  const d=await r.json().catch(()=>({}));
  if(!r.ok)throw new Error(d.error||r.statusText);return d}
async function cancelJob(queue,jobset,id){
  if(!confirm(`cancel ${id}?`))return;
  try{await post('/api/cancel',{queue,jobset,job_ids:[id]});closeDrawer();load()}
  catch(e){alert(e.message)}}
async function reprioritizeJob(queue,jobset,id){
  const p=prompt('new priority (lower schedules first)');if(p===null)return;
  try{await post('/api/reprioritize',{queue,jobset,job_ids:[id],priority:+p});
    closeDrawer();load()}catch(e){alert(e.message)}}
async function cancelJobset(queue,jobset){
  if(!confirm(`cancel every active job in ${queue}/${jobset}?`))return;
  try{await post('/api/cancel',{queue,jobset});loadJobsets()}
  catch(e){alert(e.message)}}
async function loadJobsets(){
  // Group per (queue, jobset): same-named jobsets in different queues
  // must stay separate rows (and cancel the right queue).
  const filter=document.getElementById('js-queue').value.trim();
  let queues=filter?[filter]:
    (await jget('/api/queues')).queues.map(x=>x.name);
  const aggs=encodeURIComponent(JSON.stringify(
    ['state_counts',{field:'submitted',type:'min'},{field:'submitted',type:'max'}]));
  const rows=[];
  for(const queue of queues){
    const fs=encodeURIComponent(JSON.stringify(
      [{field:'queue',value:queue,match:'exact'}]));
    const data=await jget(`/api/groups?by=jobset&aggregates=${aggs}&filters=${fs}`);
    for(const g of data.groups)rows.push({queue,g});
  }
  const fmt=t=>t?new Date(t*1000).toISOString().slice(0,19):'';
  document.querySelector('#jobsets tbody').innerHTML=rows.map(({queue,g})=>{
    const sc=g.aggregates.state_counts||{};
    const states=Object.entries(sc).map(([k,n])=>
      `<span class="state ${esc(k)}">${esc(k)} ${n}</span>`).join(' ');
    return `<tr><td>${esc(queue)}</td>
      <td>${esc(g.name)}</td><td>${g.count}</td><td>${states}</td>
      <td>${fmt(g.aggregates.submitted_min)}</td>
      <td>${fmt(g.aggregates.submitted_max)}</td>
      <td><button class="lnk">cancel</button></td></tr>`}).join('')||
    '<tr><td colspan="7">no jobsets</td></tr>';
  document.querySelectorAll('#jobsets tbody button').forEach((b,i)=>
    b.onclick=()=>cancelJobset(rows[i].queue,rows[i].g.name));
}
async function loadErrors(){
  const err=document.getElementById('errors-err');err.style.display='none';
  try{
    const data=await jget('/api/errors');
    document.querySelector('#errors tbody').innerHTML=(data.errors||[]).map(e=>
      `<tr class="row">
       <td>${esc(e.job_id)}</td><td>${esc(e.queue)}</td><td>${esc(e.jobset)}</td>
       <td>${esc(e.error_category||'')}</td>
       <td title="${esc(e.error)}">${esc((e.error||'').slice(0,160))}</td>
       </tr>`).join('')||'<tr><td colspan="5">no failed jobs</td></tr>';
    document.querySelectorAll('#errors tbody tr.row').forEach((tr,i)=>
      tr.onclick=()=>openJob(data.errors[i].job_id));
  }catch(e){err.textContent=e.message;err.style.display=''}
}
function refresh(){
  if(st.view==='jobs')load();
  else if(st.view==='groups')loadGroups();
  else if(st.view==='jobsets')loadJobsets();
  else if(st.view==='errors')loadErrors();
  else if(st.view==='queues')loadQueues();
  else loadReport()}
setInterval(()=>{if(document.getElementById('auto').checked&&
  !document.getElementById('drawer').classList.contains('open'))refresh()},3000);
show('jobs');
</script></body></html>
"""
