"""Authentication + authorization for the API surface.

Mirrors /root/reference/internal/common/auth/{multi.go,basic.go,oidc.go,
permissions.go} and the server's queue-level permission model
(pkg/client/queue permissions): a chain of authenticators resolves a
Principal from call credentials (first success wins, multi.go), and an
Authorizer grants verbs either globally (group -> permission map,
permissions.go) or per queue (queue permission subjects).

The OIDC-shaped authenticator verifies HS256 JWTs self-contained (no
external IdP dependency in this environment); the token layout (sub,
groups, exp, iss) matches what the reference extracts from OIDC claims.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field


class AuthError(Exception):
    """Unauthenticated: no authenticator produced a principal."""


class PermissionDenied(Exception):
    """Authenticated but not allowed."""


@dataclass(frozen=True)
class Principal:
    name: str
    groups: frozenset = frozenset()
    auth_method: str = ""

    def in_any(self, groups) -> bool:
        return bool(self.groups & set(groups)) or self.name in set(groups)


ANONYMOUS = Principal(name="anonymous", auth_method="anonymous")

# Global permission verbs (permissions.go).
SUBMIT_ANY_JOBS = "submit_any_jobs"
CREATE_QUEUE = "create_queue"
DELETE_QUEUE = "delete_queue"
CANCEL_ANY_JOBS = "cancel_any_jobs"
REPRIORITIZE_ANY_JOBS = "reprioritize_any_jobs"
WATCH_ALL_EVENTS = "watch_all_events"
EXECUTE_JOBS = "execute_jobs"
CORDON = "cordon"

# Queue-level verbs (queue permission model).
QUEUE_VERBS = ("submit", "cancel", "reprioritize", "watch")


class AnonymousAuth:
    """auth/anonymous: everyone is the anonymous principal."""

    def authenticate(self, metadata: dict) -> Principal | None:
        return ANONYMOUS


class BasicAuth:
    """auth/basic: username/password from an `authorization: Basic ...`
    header; users = {name: {"password": ..., "groups": [...]}}."""

    def __init__(self, users: dict):
        self.users = users

    def authenticate(self, metadata: dict) -> Principal | None:
        header = metadata.get("authorization", "")
        if not header.startswith("Basic "):
            return None
        try:
            decoded = base64.b64decode(header[6:]).decode()
            name, _, password = decoded.partition(":")
        except Exception:
            raise AuthError("malformed basic credentials")
        user = self.users.get(name)
        if user is None or user.get("password") != password:
            raise AuthError(f"invalid credentials for {name!r}")
        return Principal(
            name=name, groups=frozenset(user.get("groups", ())), auth_method="basic"
        )


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def make_token(secret: str, sub: str, groups=(), exp: float | None = None,
               iss: str = "armada-tpu") -> str:
    """Mint an HS256 JWT (test/ops helper; the CLI login flow uses it)."""
    header = {"alg": "HS256", "typ": "JWT"}
    claims = {"sub": sub, "groups": list(groups), "iss": iss}
    if exp is not None:
        claims["exp"] = exp
    signing = (
        _b64url(json.dumps(header).encode())
        + "."
        + _b64url(json.dumps(claims).encode())
    )
    sig = hmac.new(secret.encode(), signing.encode(), hashlib.sha256).digest()
    return signing + "." + _b64url(sig)


class TokenAuth:
    """auth/oidc-shaped: `authorization: Bearer <jwt>`; HS256-verified,
    claims sub/groups/exp/iss extracted like the reference's OIDC claim
    mapping (oidc.go)."""

    def __init__(self, secret: str, issuer: str = "armada-tpu"):
        self.secret = secret
        self.issuer = issuer

    def authenticate(self, metadata: dict) -> Principal | None:
        header = metadata.get("authorization", "")
        if not header.startswith("Bearer "):
            return None
        token = header[7:]
        parts = token.split(".")
        if len(parts) != 3:
            raise AuthError("malformed token")
        signing = parts[0] + "." + parts[1]
        want = hmac.new(
            self.secret.encode(), signing.encode(), hashlib.sha256
        ).digest()
        try:
            got = _unb64url(parts[2])
        except Exception:
            raise AuthError("malformed token signature")
        if not hmac.compare_digest(want, got):
            raise AuthError("bad token signature")
        try:
            claims = json.loads(_unb64url(parts[1]))
        except Exception:
            raise AuthError("malformed token claims")
        if claims.get("iss") != self.issuer:
            raise AuthError("wrong token issuer")
        exp = claims.get("exp")
        if exp is not None and time.time() > float(exp):
            raise AuthError("token expired")
        return Principal(
            name=str(claims.get("sub", "")),
            groups=frozenset(claims.get("groups", ())),
            auth_method="token",
        )


class JwksTokenAuth:
    """auth/oidc.go-shaped: `authorization: Bearer <jwt>` verified RS256
    against a JWKS document (the reference validates OIDC access tokens
    against the IdP's JWKS; this environment is zero-egress, so the JWKS
    is supplied as a dict or local file — rotate by rewriting the file,
    it is re-read when its mtime changes). Claim mapping (sub, groups,
    exp, iss) matches TokenAuth/oidc.go."""

    def __init__(
        self,
        jwks: dict | None = None,
        jwks_file: str | None = None,
        issuer: str = "armada-tpu",
        audience: str | None = None,
    ):
        if jwks is None and jwks_file is None:
            raise ValueError("JwksTokenAuth needs jwks= or jwks_file=")
        self._jwks = jwks
        self._jwks_file = jwks_file
        self._mtime = None
        self.issuer = issuer
        self.audience = audience
        self._keys: dict[str, object] = {}
        self._load()

    def _load(self):
        import os

        doc = self._jwks
        if self._jwks_file is not None:
            mtime = os.stat(self._jwks_file).st_mtime
            if mtime == self._mtime:
                return
            self._mtime = mtime
            with open(self._jwks_file) as f:
                doc = json.load(f)
        from cryptography.hazmat.primitives.asymmetric.rsa import (
            RSAPublicNumbers,
        )

        keys = {}
        for k in doc.get("keys", ()):
            if k.get("kty") != "RSA" or k.get("alg", "RS256") != "RS256":
                continue
            n = int.from_bytes(_unb64url(k["n"]), "big")
            e = int.from_bytes(_unb64url(k["e"]), "big")
            keys[k.get("kid", "")] = RSAPublicNumbers(e, n).public_key()
        self._keys = keys

    def authenticate(self, metadata: dict) -> Principal | None:
        header = metadata.get("authorization", "")
        if not header.startswith("Bearer "):
            return None
        token = header[7:]
        parts = token.split(".")
        if len(parts) != 3:
            raise AuthError("malformed token")
        try:
            hdr = json.loads(_unb64url(parts[0]))
        except Exception:
            raise AuthError("malformed token header")
        if hdr.get("alg") != "RS256":
            # Not ours — let the next authenticator (e.g. HS256) decide.
            return None
        if self._jwks_file is not None:
            # Hot-reload on rotation; a mid-rotation unreadable/partial
            # file must not take the API down — keep serving the
            # previously loaded keys until the new document is readable.
            try:
                self._load()
            except Exception:
                pass
        key = self._keys.get(hdr.get("kid", ""))
        if key is None and len(self._keys) == 1:
            key = next(iter(self._keys.values()))
        if key is None:
            raise AuthError("no JWKS key for token kid")
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        signing = (parts[0] + "." + parts[1]).encode()
        try:
            key.verify(
                _unb64url(parts[2]), signing, padding.PKCS1v15(), hashes.SHA256()
            )
        except InvalidSignature:
            raise AuthError("bad token signature")
        except Exception as e:
            raise AuthError(f"malformed token signature: {e}")
        try:
            claims = json.loads(_unb64url(parts[1]))
        except Exception:
            raise AuthError("malformed token claims")
        if claims.get("iss") != self.issuer:
            raise AuthError("wrong token issuer")
        if self.audience is not None:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.audience not in auds:
                raise AuthError("wrong token audience")
        exp = claims.get("exp")
        if exp is not None and time.time() > float(exp):
            raise AuthError("token expired")
        return Principal(
            name=str(claims.get("sub", "")),
            groups=frozenset(claims.get("groups", ())),
            auth_method="jwks",
        )


def make_rs256_token(private_key, sub: str, groups=(), exp=None,
                     iss: str = "armada-tpu", kid: str = "k1", aud=None) -> str:
    """Mint an RS256 JWT (test/ops helper; private_key is a cryptography
    RSAPrivateKey)."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    header = {"alg": "RS256", "typ": "JWT", "kid": kid}
    claims = {"sub": sub, "groups": list(groups), "iss": iss}
    if exp is not None:
        claims["exp"] = exp
    if aud is not None:
        claims["aud"] = aud
    signing = (
        _b64url(json.dumps(header).encode())
        + "."
        + _b64url(json.dumps(claims).encode())
    )
    sig = private_key.sign(signing.encode(), padding.PKCS1v15(), hashes.SHA256())
    return signing + "." + _b64url(sig)


def jwks_of(public_key, kid: str = "k1") -> dict:
    """The JWKS document for an RSA public key (test/ops helper)."""
    nums = public_key.public_numbers()

    def be(i: int) -> str:
        return _b64url(i.to_bytes((i.bit_length() + 7) // 8, "big"))

    return {
        "keys": [
            {"kty": "RSA", "alg": "RS256", "use": "sig", "kid": kid,
             "n": be(nums.n), "e": be(nums.e)}
        ]
    }


class MultiAuth:
    """auth/multi.go: try each authenticator in order; the first that
    recognises the credential shape decides; none matching -> error."""

    def __init__(self, authenticators: list):
        self.authenticators = list(authenticators)

    def authenticate(self, metadata: dict) -> Principal:
        for auth in self.authenticators:
            principal = auth.authenticate(metadata or {})
            if principal is not None:
                return principal
        raise AuthError("no credentials accepted by any authenticator")


@dataclass(frozen=True)
class QueuePermission:
    """One queue permission grant (pkg/client/queue Permissions)."""

    subjects: tuple = ()  # user or group names
    verbs: tuple = QUEUE_VERBS


@dataclass
class Authorizer:
    """permissions.go: group -> global permission map, plus per-queue
    grants resolved through the queue registry."""

    # {permission: [group-or-user, ...]}
    permission_groups: dict = field(default_factory=dict)
    # Groups holding every permission (the reference's admin mapping).
    admin_groups: tuple = ("admin",)

    def has_global(self, principal: Principal, permission: str) -> bool:
        if principal.in_any(self.admin_groups):
            return True
        return principal.in_any(self.permission_groups.get(permission, ()))

    def authorize_global(self, principal: Principal, permission: str):
        if not self.has_global(principal, permission):
            raise PermissionDenied(
                f"{principal.name} lacks permission {permission}"
            )

    def authorize_queue(
        self, principal: Principal, verb: str, queue, global_permission: str
    ):
        """Queue-scoped action: allowed by the global permission, queue
        ownership, or a queue permission grant naming the principal."""
        if self.has_global(principal, global_permission):
            return
        owners = getattr(queue, "owners", ()) if queue is not None else ()
        if principal.in_any(owners):
            return
        for grant in getattr(queue, "permissions", ()) if queue is not None else ():
            if verb in grant.verbs and principal.in_any(grant.subjects):
                return
        raise PermissionDenied(
            f"{principal.name} may not {verb} on queue "
            f"{getattr(getattr(queue, 'spec', None), 'name', '?')}"
        )
