"""gRPC API: remote submit/query/events/reports surface.

Plays the role of the reference's gRPC services (Submit/QueueService/
Event/Jobs, /root/reference/pkg/api/submit.proto:356-401, event.proto:279,
job.proto:102). Methods are hosted with grpc generic handlers and
JSON-encoded messages: same capability surface (remote clients, streaming
watch) without a protoc codegen step; a protobuf wire encoding can be added
as an alternate content type behind the same method table.
"""

from __future__ import annotations

import contextvars
import dataclasses
import json
import threading
import time as _time

import grpc

from ..frontdoor.admission import AdmissionError, DeadlineExpired
from ..core.types import (
    Affinity,
    Gang,
    IngressConfig,
    JobSpec,
    MatchExpression,
    NodeSelectorTerm,
    QueueSpec,
    ServiceConfig,
    Toleration,
)
from ..jobdb import JobState
from ..utils.tracing import TRACEPARENT_HEADER, TRACER
from .queryapi import JobFilter, Order

SERVICE = "armada_tpu.Api"
# Binary-protobuf twin of the method table (proto/armada.proto): codegen
# clients in any protobuf language hit the same handlers through it.
PROTO_SERVICE = "armada_tpu.ProtoApi"


class FencedError(RuntimeError):
    """A lease/report call carried a fencing token older than the
    executor's current fence: the scheduler already reassigned that
    executor's runs (partition expiry), so the stale exchange must not
    land. Mapped to FAILED_PRECONDITION on both wire encodings; the
    agent's recovery is an anti-entropy ExecutorSync, which returns the
    current token."""


def is_fenced_error(exc) -> bool:
    """True for a FencedError raised in-process OR its FAILED_PRECONDITION
    image on the wire (what ApiClient/ProtoExecutorClient callers see)."""
    if isinstance(exc, FencedError):
        return True
    code = getattr(exc, "code", None)
    try:
        return callable(code) and code() == grpc.StatusCode.FAILED_PRECONDITION
    except Exception:
        return False


# Absolute deadline (unix seconds) of the in-flight RPC, set by the unary
# wrappers from gRPC's propagated client deadline (context.time_remaining)
# so handlers — the submit path — can drop already-expired work early
# instead of half-processing it. None = the caller set no deadline.
_CALL_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    "armada_call_deadline", default=None
)

# Trailing-metadata key carrying the server-computed earliest useful retry
# instant on RESOURCE_EXHAUSTED shed responses; ApiClient/ProtoApiClient
# honor it with a bounded jittered backoff.
RETRY_AFTER_KEY = "retry-after"


def _retry_after_of(exc) -> float | None:
    """Seconds the server asked us to wait, from a RESOURCE_EXHAUSTED
    RpcError's trailing metadata — None for every other failure (other
    codes, or exhaustion without a hint, e.g. a full what-if backlog)."""
    code = getattr(exc, "code", None)
    try:
        if not callable(code) or code() != grpc.StatusCode.RESOURCE_EXHAUSTED:
            return None
        tm = getattr(exc, "trailing_metadata", None)
        md = tm() if callable(tm) else None
        for key, value in md or ():
            if key.lower() == RETRY_AFTER_KEY:
                return max(0.0, float(value))
    except (TypeError, ValueError):
        return None
    return None


def _call_deadline(context) -> object:
    """Stamp the RPC's absolute deadline into _CALL_DEADLINE; returns the
    reset token (None when the caller set no deadline)."""
    remaining = context.time_remaining()
    if remaining is None:
        return None
    return _CALL_DEADLINE.set(_time.time() + remaining)


def _rpc_span(method: str, context):
    """Server span for one RPC, joined to the caller's trace via the
    W3C `traceparent` call metadata (the server-interceptor half of
    trace propagation; ApiClient/ProtoApiClient inject the header).
    Handlers run inside it, so anything they publish — e.g. a submit's
    EventSequence — can stamp the same trace id."""
    md = {k.lower(): v for k, v in (context.invocation_metadata() or ())}
    return TRACER.span(
        f"rpc.{method}",
        remote_parent=md.get(TRACEPARENT_HEADER),
        rpc=method,
    )


def _inject_traceparent(metadata: list | None) -> list | None:
    """Client-side half: append the current span's traceparent to the
    outgoing call metadata (no-op outside any span)."""
    tp = TRACER.current_traceparent()
    if not tp:
        return metadata
    return list(metadata or []) + [(TRACEPARENT_HEADER, tp)]


def _encode(obj) -> bytes:
    def default(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return dataclasses.asdict(o)
        if isinstance(o, JobState):
            return o.value
        if hasattr(o, "tolist"):
            return o.tolist()
        raise TypeError(f"unserializable {type(o)}")

    return json.dumps(obj, default=default).encode()


def _decode(data: bytes):
    return json.loads(data.decode()) if data else {}


def job_spec_from_dict(d: dict) -> JobSpec:
    gang = None
    if d.get("gang"):
        g = d["gang"]
        gang = Gang(
            id=g["id"],
            cardinality=int(g["cardinality"]),
            node_uniformity_label=g.get("node_uniformity_label", ""),
        )
    tolerations = tuple(
        Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Equal"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
        )
        for t in d.get("tolerations", ())
    )
    affinity = None
    if d.get("affinity"):
        raw = d["affinity"]
        # Two accepted shapes: the legacy JSON list-of-term-lists, and the
        # proto json_format mapping {"terms": [{"expressions": [...]}]}.
        terms = (
            [t.get("expressions", ()) for t in raw.get("terms", ())]
            if isinstance(raw, dict)
            else raw
        )
        affinity = Affinity(
            terms=tuple(
                NodeSelectorTerm(
                    expressions=tuple(
                        MatchExpression(
                            key=e["key"],
                            operator=e.get("operator", "In"),
                            values=tuple(str(v) for v in e.get("values", ())),
                        )
                        for e in term
                    )
                )
                for term in terms
            )
        )
    return JobSpec(
        id=d.get("id", ""),
        queue=d.get("queue", ""),
        jobset=d.get("jobset", ""),
        pools=tuple(d.get("pools", ())),
        priority=int(d.get("priority", 0)),
        priority_class=d.get("priority_class", ""),
        requests=dict(d.get("requests", {})),
        node_selector=dict(d.get("node_selector", {})),
        tolerations=tolerations,
        affinity=affinity,
        gang=gang,
        annotations=dict(d.get("annotations", {})),
        bid_prices={
            # Accept the proto json_format shape {"queued": q, "running": r}
            # alongside scalars and (queued, running) pairs; normalize to
            # the pair form bid_price_pair understands.
            k: (
                (float(v.get("queued", 0.0)), float(v.get("running", 0.0)))
                if isinstance(v, dict)
                else v
            )
            for k, v in dict(d.get("bid_prices", {})).items()
        },
        command=tuple(d.get("command", ())),
        services=tuple(
            ServiceConfig.from_obj(s) for s in d.get("services", ())
        ),
        ingresses=tuple(
            IngressConfig.from_obj(i) for i in d.get("ingresses", ())
        ),
    )


def _lease_req_from_proto_dict(req: dict) -> dict:
    """LeaseRequest json_format dict -> the JSON handler's layout: unwrap
    the map<int32, ResourceMap> nesting (json_format keys maps by string)."""
    for node in req.get("nodes", ()):
        unalloc = node.get("unallocatable_by_priority")
        if unalloc:
            node["unallocatable_by_priority"] = {
                k: dict(v.get("resources", {})) for k, v in unalloc.items()
            }
    return req


def _lease_resp_to_proto_dict(out: dict) -> dict:
    """JSON lease reply -> LeaseResponse-shaped dict: the jobspec travels
    as always-zlib bytes on the proto wire (base64 for ParseDict), like
    the reference's compressed lease payloads."""
    import base64
    import zlib

    leases = []
    for lease in out.get("leases", ()):
        lease = dict(lease)
        spec = lease.pop("spec", None)
        if isinstance(spec, dict) and "__zlib__" in spec:
            raw = base64.b64decode(spec["__zlib__"])
        else:
            raw = zlib.compress(json.dumps(spec).encode(), level=6)
        lease["spec_zlib"] = base64.b64encode(raw).decode()
        leases.append(lease)
    return {**out, "leases": leases}


def _whatif_req_from_proto_dict(req: dict) -> dict:
    """WhatIf/PlanDrain/ExecuteDrain json_format dict -> the JSON
    handler's layout: zero-valued optional scalars mean "unset" on the
    proto wire (proto3 has no presence for them), so strip them and the
    handlers apply the configured defaults."""
    out = {k: v for k, v in req.items() if v not in ("", 0, 0.0, False)}
    mutations = [
        {k: v for k, v in m.items() if v not in ("", 0, 0.0, False)}
        for m in req.get("mutations", ())
    ]
    if mutations:
        out["mutations"] = mutations
    return out


def _plan_resp_to_proto_dict(out: dict) -> dict:
    return {
        "plan_json": json.dumps(out.get("plan") or {}, default=str),
        "rendered": out.get("rendered", ""),
    }


def _status_resp_to_proto_dict(out: dict) -> dict:
    return {"status_json": json.dumps(out.get("status") or {}, default=str)}


class ProtoExecutorClient:
    """Executor-agent connector over the binary-protobuf wire: implements
    the agent's `_call` surface (ExecutorLease / ReportEvents) with
    LeaseRequest/LeaseResponse messages — what a non-Python executor
    build against proto/armada.proto speaks."""

    def __init__(self, target: str, token: str | None = None,
                 ca_cert: str | None = None):
        self._proto = ProtoApiClient(target, token=token, ca_cert=ca_cert)

    def _call(self, method: str, req: dict):
        from google.protobuf import json_format

        from ..proto import armada_pb2 as pb

        if method == "ExecutorLease":
            msg = pb.LeaseRequest(
                executor=req["executor"],
                pool=req.get("pool", "default"),
                acked_run_ids=list(req.get("acked_run_ids", ())),
                fence_token=int(req.get("fence_token", 0) or 0),
            )
            for n in req.get("nodes", ()):
                node = msg.nodes.add(
                    id=n["id"],
                    name=n.get("name", n["id"]),
                    pool=n.get("pool", ""),
                    unschedulable=bool(n.get("unschedulable", False)),
                )
                node.labels.update(
                    {k: str(v) for k, v in (n.get("labels") or {}).items()}
                )
                node.total_resources.update(
                    {
                        k: str(v)
                        for k, v in (n.get("total_resources") or {}).items()
                    }
                )
                node.usage.update(
                    {k: str(v) for k, v in (n.get("usage") or {}).items()}
                )
                for t in n.get("taints", ()):
                    node.taints.add(
                        key=t.get("key", ""),
                        value=t.get("value", ""),
                        effect=t.get("effect", "NoSchedule"),
                    )
                for prio, res in (
                    n.get("unallocatable_by_priority") or {}
                ).items():
                    node.unallocatable_by_priority[int(prio)].resources.update(
                        {k: str(v) for k, v in res.items()}
                    )
            resp = self._proto._unary("ExecutorLease", msg, pb.LeaseResponse)
            out = json_format.MessageToDict(
                resp,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            )
            # spec_zlib bytes -> the JSON wire's {"__zlib__": b64} shape,
            # which the agent's decompress_obj already understands.
            for lease in out.get("leases", ()):
                lease["spec"] = {"__zlib__": lease.pop("spec_zlib", "")}
            return out
        if method == "ReportEvents":
            msg = pb.ReportEventsRequest(
                executor=str(req.get("executor", "")),
                fence_token=int(req.get("fence_token", 0) or 0),
            )
            for e in req.get("events", ()):
                msg.events.add(
                    type=e.get("type", ""),
                    job_id=e.get("job_id", ""),
                    run_id=e.get("run_id", ""),
                    queue=e.get("queue", ""),
                    jobset=e.get("jobset", ""),
                    created=float(e.get("created", 0.0)),
                    error=str(e.get("error", "")),
                    retryable=bool(e.get("retryable", True)),
                    debug=str(e.get("debug", "")),
                )
            self._proto._unary("ReportEvents", msg, pb.ReportEventsResponse)
            return {}
        if method == "ExecutorSync":
            msg = pb.ExecutorSyncRequest(executor=req["executor"])
            for r in req.get("runs", ()):
                msg.runs.add(
                    run_id=r.get("run_id", ""),
                    job_id=r.get("job_id", ""),
                    phase=r.get("phase", ""),
                )
            resp = self._proto._unary(
                "ExecutorSync", msg, pb.ExecutorSyncResponse
            )
            return json_format.MessageToDict(
                resp,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            )
        raise ValueError(f"ProtoExecutorClient does not speak {method!r}")


class ApiServer:
    """Hosts submit/query/events/reports over one gRPC server."""

    def __init__(
        self,
        submit,
        scheduler,
        query,
        log,
        submit_checker=None,
        binoculars=None,
        auth=None,
        authorizer=None,
        event_index=None,
        store_health=None,
        frontdoor=None,
    ):
        self.submit = submit
        self.scheduler = scheduler
        self.query = query
        self.log = log
        self.submit_checker = submit_checker
        self.binoculars = binoculars
        # Optional front door (armada_tpu/frontdoor): the submit handler
        # observes its latency histogram and counts deadline drops
        # against it; admission itself runs inside SubmitService.submit
        # (one enforcement point for every transport).
        self.frontdoor = frontdoor
        # Optional backpressure monitor (services/backpressure.py):
        # surfaced to executors in lease replies.
        self.store_health = store_health
        # Optional per-jobset event-stream index (services/event_index.py,
        # the event-ingester view): watchers read only their jobset's
        # offsets instead of scanning the whole log.
        self.event_index = event_index
        # Authentication chain + permission mapping (services/auth.py;
        # common/auth/{multi,permissions}.go). None = open server (tests,
        # trusted in-process deployments).
        self.auth = auth
        self.authorizer = authorizer
        # Per-executor circuit breaker on the lease path: an executor whose
        # exchanges keep failing (malformed payloads, injected faults) gets
        # fast-failed with UNAVAILABLE for a cooldown — absorbed by the
        # agent's backoff loop — instead of repeatedly erroring a worker
        # thread mid-cycle (services/chaos.py).
        from .chaos import CircuitBreaker

        self.lease_breaker = CircuitBreaker(
            failure_threshold=3, cooldown_s=30.0
        )

    def _authorize(self, method: str, principal, req: dict):
        """Per-method permission gate (the reference's auth interceptors +
        per-handler authorize calls, server/submit.go)."""
        from . import auth as A

        az = self.authorizer
        if az is None or principal is None:
            return
        queue = None
        if "queue" in req and self.submit is not None:
            queue = self.submit.get_queue(req.get("queue", ""))
        if method == "SubmitJobs":
            az.authorize_queue(principal, "submit", queue, A.SUBMIT_ANY_JOBS)
        elif method == "CancelJobs":
            az.authorize_queue(principal, "cancel", queue, A.CANCEL_ANY_JOBS)
        elif method == "ReprioritizeJobs":
            az.authorize_queue(
                principal, "reprioritize", queue, A.REPRIORITIZE_ANY_JOBS
            )
        elif method in ("CreateQueue", "UpdateQueue"):
            az.authorize_global(principal, A.CREATE_QUEUE)
        elif method == "DeleteQueue":
            az.authorize_global(principal, A.DELETE_QUEUE)
        elif method in (
            "CordonNode", "CordonExecutor", "SetPriorityOverride", "PolicySet"
        ):
            # A fairness-policy flip reshapes every queue's entitlement —
            # the same operator privilege as cordon/override writes.
            az.authorize_global(principal, A.CORDON)
        elif method == "ExecuteDrain":
            # Draining cordons + preempts: the same privilege as cordon.
            # WhatIf/PlanDrain are read-only shadow solves — any
            # authenticated principal may ask.
            az.authorize_global(principal, A.CORDON)
        elif method in ("ExecutorLease", "ReportEvents"):
            az.authorize_global(principal, A.EXECUTE_JOBS)
        elif method == "WatchJobSet":
            az.authorize_queue(principal, "watch", queue, A.WATCH_ALL_EVENTS)
        # Reads (GetQueue/ListQueues/GetJobs/reports/logs) require only an
        # authenticated principal.

    # ---- unary handlers ----

    def _submit_jobs(self, req):
        """Submit with the front door's protections when one is wired:
        the propagated client deadline gates entry (expired work drops
        before any processing — stage "gate" — or just before the WAL
        ack — stage "enqueue"), admission sheds with AdmissionError
        (RESOURCE_EXHAUSTED + retry-after on the wire), and the handler
        wall clock lands in frontdoor_submit_seconds by outcome."""
        fd = self.frontdoor
        metrics = getattr(fd, "metrics", None) if fd is not None else None
        started = _time.perf_counter()
        # "error" covers everything that is neither an ack nor a
        # deliberate shed/expiry (validation rejections, unknown queue):
        # those requests were never acked and must not skew the ok-path
        # ack-latency SLO.
        outcome = "error"
        try:
            deadline_ts = req.get("deadline_ts") or _CALL_DEADLINE.get()
            deadline_ts = float(deadline_ts) if deadline_ts else None
            if deadline_ts is not None and _time.time() >= deadline_ts:
                if fd is not None:
                    fd.note_deadline_drop("gate")
                raise DeadlineExpired(
                    "gate", "client deadline expired before admission"
                )
            jobs = [
                job_spec_from_dict(j).with_(
                    queue=req["queue"], jobset=req["jobset"]
                )
                for j in req["jobs"]
            ]
            if self.submit_checker is not None:
                check = self.submit_checker.check(jobs)
                if not check.schedulable:
                    raise ValueError(
                        f"jobs would never schedule: {check.reason}"
                    )
            ids = self.submit.submit(
                req["queue"], req["jobset"], jobs, deadline_ts=deadline_ts
            )
            outcome = "ok"
            return {"job_ids": ids}
        except AdmissionError:
            outcome = "shed"
            raise
        except DeadlineExpired:
            outcome = "expired"
            raise
        finally:
            if metrics is not None and metrics.registry is not None:
                metrics.frontdoor_submit_time.labels(
                    outcome=outcome
                ).observe(_time.perf_counter() - started)

    def _cancel_jobs(self, req):
        for job_id in req.get("job_ids", []):
            self.submit.cancel_job(
                req["queue"], req["jobset"], job_id, req.get("reason", "")
            )
        if req.get("cancel_jobset"):
            self.submit.cancel_jobset(req["queue"], req["jobset"], req.get("reason", ""))
        return {}

    def _reprioritize(self, req):
        for job_id in req.get("job_ids", []):
            self.submit.reprioritise_job(
                req["queue"], req["jobset"], job_id, int(req["priority"])
            )
        return {}

    def _create_queue(self, req):
        self.submit.create_queue(
            QueueSpec(req["name"], float(req.get("priority_factor", 1.0))),
            cordoned=bool(req.get("cordoned", False)),
        )
        return {}

    def _update_queue(self, req):
        pf = req.get("priority_factor")
        self.submit.update_queue(
            req["name"],
            priority_factor=float(pf) if pf is not None else None,
            cordoned=req.get("cordoned"),
        )
        return {}

    def _delete_queue(self, req):
        self.submit.delete_queue(req["name"])
        return {}

    def _get_queue(self, req):
        q = self.submit.get_queue(req["name"])
        if q is None:
            raise KeyError(f"queue {req['name']!r} not found")
        return {
            "name": q.spec.name,
            "priority_factor": q.spec.priority_factor,
            "cordoned": q.cordoned,
        }

    def _list_queues(self, req):
        return {
            "queues": [
                {
                    "name": q.spec.name,
                    "priority_factor": q.spec.priority_factor,
                    "cordoned": q.cordoned,
                }
                for q in self.submit.queues.values()
            ]
        }

    def _get_jobs(self, req):
        filters = [
            JobFilter(f["field"], f.get("value"), f.get("match", "exact"))
            for f in req.get("filters", [])
        ]
        order = Order(
            req.get("order_field", "submitted"), req.get("order_direction", "asc")
        )
        rows, total = self.query.get_jobs(
            filters, order, int(req.get("skip", 0)), int(req.get("take", 100))
        )
        return {"jobs": [dataclasses.asdict(r) for r in rows], "total": total}

    def _group_jobs(self, req):
        filters = [
            JobFilter(f["field"], f.get("value"), f.get("match", "exact"))
            for f in req.get("filters", [])
        ]
        return {
            "groups": self.query.group_jobs(
                req["group_by"], filters, req.get("aggregates", [])
            )
        }

    def _proxy_to_leader(self, method: str, req: dict):
        """Reports describe the LEADER's rounds: a follower in file-lease
        HA mode forwards report RPCs to the leader's advertised address
        (the reference proxies via the Lease-holder connection,
        internal/scheduler/reports client). Returns None when this
        instance should answer locally (it is the leader, the address is
        unknown, or it would dial itself)."""
        elector = getattr(self.scheduler, "is_leader", None)
        is_holder = getattr(elector, "is_holder", None)
        if elector is None or is_holder is None or is_holder():
            return None
        addr = getattr(elector, "leader_address", lambda: "")()
        if not addr or addr == getattr(elector, "advertise", ""):
            return None
        # One cached channel per leader address (a new channel per polled
        # report RPC would leak fds on followers).
        cached = getattr(self, "_leader_client", None)
        if cached is None or cached[0] != addr:
            if cached is not None:
                cached[1].channel.close()
            cached = (addr, ApiClient(addr))
            self._leader_client = cached
        try:
            return cached[1]._call(method, req)
        except Exception:
            return None  # leader unreachable: serve the local (stale) view

    def _scheduling_report(self, req):
        proxied = self._proxy_to_leader("SchedulingReport", req)
        if proxied is not None:
            return proxied
        return {"report": self.scheduler.reports.scheduling_report()}

    def _queue_report(self, req):
        proxied = self._proxy_to_leader("QueueReport", req)
        if proxied is not None:
            return proxied
        return {"report": self.scheduler.reports.queue_report(req["queue"])}

    def _job_report(self, req):
        proxied = self._proxy_to_leader("JobReport", req)
        if proxied is not None:
            return proxied
        return {"report": self.scheduler.reports.job_report(req["job_id"])}

    def _job_trace(self, req):
        """One job's end-to-end journey (services/job_timeline.py):
        every state transition plus the aggregated unschedulable-round
        history and the submit trace id. Proxied to the leader like the
        reports — the ledger describes the leader's rounds."""
        proxied = self._proxy_to_leader("JobTrace", req)
        if proxied is not None:
            return proxied
        timeline = getattr(self.scheduler, "timeline", None)
        if timeline is None:
            raise KeyError("job timeline not enabled")
        doc = timeline.get(req["job_id"])
        if doc is None:
            raise KeyError(f"no journey recorded for job {req['job_id']!r}")
        return {
            "journey": doc,
            "rendered": timeline.render(req["job_id"], doc=doc),
        }

    def _slo_status(self, req):
        """Declared SLOs with compliance + multi-window burn rates
        (services/slo.py). Leader-proxied like the reports — burn rates
        describe the leader's rounds, a follower's tracker is idle."""
        proxied = self._proxy_to_leader("SLOStatus", req)
        if proxied is not None:
            return proxied
        tracker = getattr(self.scheduler, "slo", None)
        if tracker is None:
            raise KeyError("SLO tracking not enabled on this server")
        return tracker.snapshot()

    def _doctor(self, req):
        """Self-healing-solve state (scheduler.doctor_report): failover
        ladder breaker states, recent admission-firewall rejections with
        their quarantine bundle paths, recent failovers. Leader-proxied
        — the ladder describes the leader's rounds."""
        proxied = self._proxy_to_leader("Doctor", req)
        if proxied is not None:
            return proxied
        report = getattr(self.scheduler, "doctor_report", None)
        if report is None:
            raise KeyError("doctor report not available on this server")
        return report()

    def _fairness_report(self, req):
        """Fairness observatory (observe/fairness.py): the latest per
        -pool share ledger, preemption attribution map and starvation
        alerts. Leader-proxied like the reports — the ledger describes
        the leader's rounds. Optional req["pool"] narrows to one pool
        (NOT_FOUND when no round has solved for it)."""
        proxied = self._proxy_to_leader("FairnessReport", req)
        if proxied is not None:
            return proxied
        tracker = getattr(self.scheduler, "fairness", None)
        if tracker is None:
            raise KeyError("fairness observatory not enabled on this server")
        pool = req.get("pool") or None
        if pool:
            doc = tracker.latest(pool)
            if doc is None:
                raise KeyError(f"no fairness ledger recorded for pool {pool!r}")
            snap = tracker.snapshot()
            return {
                "pools": {pool: doc},
                "alerts": [a for a in snap["alerts"] if a["pool"] == pool],
            }
        return tracker.snapshot()

    # ---- what-if planner (armada_tpu/whatif) ----

    def _whatif_service(self):
        svc = getattr(self.scheduler, "whatif", None)
        if svc is None:
            raise KeyError("what-if planner not enabled on this server")
        return svc

    @staticmethod
    def _opt_float(req, key):
        value = req.get(key)
        return float(value) if value is not None else None

    def _what_if(self, req):
        """Shadow-solve a mutated fork of the last round and return the
        structured plan (displacements, gang ETAs, headroom). Runs on
        the planner's bounded worker — a full backlog fails fast with
        RESOURCE_EXHAUSTED instead of queueing."""
        from ..whatif import mutations_from_dicts

        svc = self._whatif_service()
        plan = svc.plan(
            mutations_from_dicts(req.get("mutations", [])),
            pool=req.get("pool") or None,
            solver=req.get("solver") or None,
            rounds=int(req["rounds"]) if req.get("rounds") else None,
        )
        return {"plan": plan.to_dict(), "rendered": plan.render()}

    def _plan_drain(self, req):
        """Dry-run a drain: predicted voluntary completions, deadline
        preemptions (gang-aware), requeue landings, rounds-to-drain —
        produced by the SAME DrainController execution runs."""
        svc = self._whatif_service()
        plan = svc.plan_drain(
            req["executor"],
            pool=req.get("pool") or None,
            solver=req.get("solver") or None,
            rounds=int(req["rounds"]) if req.get("rounds") else None,
            deadline_s=self._opt_float(req, "deadline_s"),
        )
        return {"plan": plan.to_dict(), "rendered": plan.render()}

    def _execute_drain(self, req):
        """Start (idempotent) or poll a REAL staged drain through the
        control-plane event path."""
        svc = self._whatif_service()
        if req.get("status_only"):
            status = svc.drain_status(req.get("executor") or None)
            if status is None:
                raise KeyError(
                    f"no drain recorded for executor {req.get('executor')!r}"
                )
            return {"status": status}
        status = svc.execute_drain(
            req["executor"], deadline_s=self._opt_float(req, "deadline_s")
        )
        return {"status": status}

    def _set_priority_override(self, req):
        self.scheduler.set_priority_override(
            req["queue"], req.get("priority_factor")
        )
        return {}

    def _list_priority_overrides(self, req):
        return {"overrides": dict(self.scheduler.priority_overrides)}

    # ---- fairness policy control plane (solver/policy.py) ----

    def _policy_show(self, req):
        """Active fairness policy per pool: the file-config layer, the
        runtime overrides, and the effective policy each pool solves
        under. Optional req["pool"] narrows to one pool."""
        cfg = self.scheduler.config
        pools = {p.name for p in cfg.pools} | set(
            cfg.fairness_policy_pools
        ) | set(self.scheduler.fairness_policy_overrides)
        want = req.get("pool") or None
        if want is not None:
            if want not in pools:
                pools = pools | {want}
            pools = {want}
        return {
            "default": str(cfg.fairness_policy_default),
            "overrides": dict(self.scheduler.fairness_policy_overrides),
            "pools": {
                pool: self.scheduler.fairness_policy(pool)
                for pool in sorted(pools)
            },
        }

    def _policy_set(self, req):
        """Flip (or clear, policy="") a pool's fairness policy. The
        divergence gate applies unless force=True: a non-DRF flip needs
        a registered shadow scorecard (see `armadactl policy ab`)."""
        pool = req["pool"]
        policy = req.get("policy") or None
        scorecard = req.get("scorecard")
        if scorecard and policy:
            self.scheduler.note_policy_shadow(pool, policy, scorecard)
        self.scheduler.set_fairness_policy(
            pool, policy, force=bool(req.get("force"))
        )
        return {"pool": pool, "policy": self.scheduler.fairness_policy(pool)}

    def _cordon_executor(self, req):
        self.scheduler.set_executor_cordon(
            req["executor"], not req.get("uncordon", False)
        )
        return {}

    # ---- executor API (the LeaseJobRuns protocol,
    # pkg/executorapi/executorapi.proto:106-115) ----

    def _executor_lease(self, req):
        """One heartbeat exchange behind the per-executor circuit breaker:
        open circuits fast-fail the RPC (UNAVAILABLE — wire-agnostic: a
        reply-payload flag would be dropped by the proto LeaseResponse
        schema) and the agent's backoff loop absorbs it; failures count
        toward opening; a success closes the circuit."""
        from .chaos import CircuitOpenError

        name = req.get("executor", "")
        if not self.lease_breaker.allow(name):
            raise CircuitOpenError(
                f"lease circuit open for executor {name!r}; retry after "
                f"{self.lease_breaker.cooldown_s:.0f}s cooldown"
            )
        # Fence gate BEFORE the exchange touches scheduler state: a
        # stale-fenced executor heartbeating would otherwise re-enter the
        # heartbeat map and receive leases the anti-entropy sync hasn't
        # validated. A fence rejection is protocol, not a server fault —
        # it must not open the circuit.
        self._check_fence("ExecutorLease", name, req.get("fence_token"))
        try:
            reply = self._executor_lease_inner(req)
        except Exception:
            self.lease_breaker.record_failure(name)
            raise
        self.lease_breaker.record_success(name)
        return reply

    def _check_fence(self, method: str, name: str, token) -> None:
        """Reject tokens older than the executor's current fence. Tokens
        are optional (None/absent skips the check) so pre-fencing clients
        and in-process callers keep working; an executor that was fenced
        while holding no token (agent restart) sends 0 and is routed
        through ExecutorSync like any stale holder."""
        fence_of = getattr(self.scheduler, "executor_fence", None)
        if not name or token is None or fence_of is None:
            return
        current = fence_of(name)
        if int(token) < current:
            metrics = getattr(self.scheduler, "metrics", None)
            if metrics is not None and metrics.registry is not None:
                metrics.fence_rejections.labels(
                    executor=name, method=method
                ).inc()
            raise FencedError(
                f"executor {name!r} holds fence token {int(token)} but the "
                f"scheduler is at {current} (runs were reassigned after a "
                "partition); complete an ExecutorSync before leasing or "
                "reporting"
            )

    def _executor_lease_inner(self, req):
        """One heartbeat exchange: the executor reports its nodes and acked
        run ids; the reply carries new leases and runs to cancel/preempt."""
        from ..core.types import NodeSpec, Taint
        from ..jobdb import JobState
        from .scheduler import ExecutorHeartbeat

        name = req["executor"]
        pool = req.get("pool", "default")
        nodes = [
            NodeSpec(
                id=n["id"],
                name=n.get("name", n["id"]),
                executor=name,
                # Per-node pool override (node_group.go GetPool: pool label
                # + reserved suffix): one cluster can span pools.
                pool=n.get("pool", pool),
                labels=dict(n.get("labels", {})),
                taints=tuple(
                    Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                    for t in n.get("taints", ())
                ),
                total_resources=dict(n.get("total_resources", {})),
                unschedulable=bool(n.get("unschedulable", False)),
                # Utilisation reporting: the non-framework slice arrives as
                # unallocatable-at-every-priority (executor/utilisation/).
                unallocatable_by_priority={
                    int(k): dict(v)
                    for k, v in n.get("unallocatable_by_priority", {}).items()
                },
            )
            for n in req.get("nodes", [])
        ]
        import time as _t

        self.scheduler.report_executor(
            ExecutorHeartbeat(name=name, pool=pool, nodes=nodes, last_seen=_t.time())
        )

        acked = set(req.get("acked_run_ids", []))
        leases, cancels, active = [], [], []
        # Store backpressure (services/backpressure.py — the reference's
        # executor pauses pod creation on etcd pressure,
        # executor/application.go:63-101): checked up front so an
        # unhealthy reply skips building (and compressing) lease payloads
        # the agent would discard anyway. Cancels and reconciliation still
        # flow — they relieve pressure.
        store_healthy = True
        if self.store_health is not None:
            store_healthy, _ = self.store_health.check()
        txn = self.scheduler.jobdb.read_txn()
        # Live runs on this executor come from the by-executor index; the
        # cancel sweep below resolves acked run ids directly (no full-store
        # walk on the lease hot path).
        for job in txn.jobs_for_executor(name):
            run = job.latest_run
            if run is None or run.executor != name:
                continue
            if (
                store_healthy
                and job.state == JobState.LEASED
                and run.id not in acked
            ):
                from ..utils.compress import compress_obj

                leases.append(
                    {
                        "run_id": run.id,
                        "job_id": job.id,
                        "queue": job.queue,
                        "jobset": job.jobset,
                        "node_id": run.node_id,
                        "scheduled_at_priority": run.scheduled_at_priority,
                        # Jobspecs dominate lease payloads; compressed like
                        # the reference's zlib-compressed lease replies
                        # (common/compress, scheduler/api.go).
                        "spec": compress_obj(
                            {
                                "id": job.spec.id,
                                "requests": job.spec.requests,
                                "annotations": job.spec.annotations,
                                "command": list(job.spec.command),
                                "services": [
                                    dataclasses.asdict(s)
                                    for s in job.spec.services
                                ],
                                "ingresses": [
                                    dataclasses.asdict(i)
                                    for i in job.spec.ingresses
                                ],
                            }
                        ),
                    }
                )
            elif job.state in (JobState.PENDING, JobState.RUNNING):
                # Runs the server believes are live here: the agent
                # reconciles pods it doesn't actually have (restart/loss).
                active.append(
                    {
                        "run_id": run.id,
                        "job_id": job.id,
                        "queue": job.queue,
                        "jobset": job.jobset,
                    }
                )
        # Jobs killed underneath the executor: tear the pod down
        # (SUCCEEDED pods exit on their own; no cancel for them). The acked
        # gate is both necessary and sufficient: the agent's acked set IS
        # its live-pod set (executor_agent.py prunes acks to live pods
        # every tick), so a pod started from a prior exchange whose job was
        # cancelled mid-flight appears in acked on the NEXT exchange and
        # gets its cancel then; and runs that never produced a pod never
        # trigger resends. Resolved per acked run id via the run index.
        for rid in acked:
            job = txn.job_for_any_run(rid)
            if job is None:
                continue
            owned = next((r for r in job.runs if r.id == rid), None)
            if owned is None or owned.executor != name:
                continue
            from ..jobdb.jobdb import RunState as _RS

            if job.state in (
                JobState.CANCELLED,
                JobState.PREEMPTED,
                JobState.FAILED,
            ) or owned.state == _RS.PREEMPTED:
                # Job killed underneath the executor — or the RUN alone
                # was preempt-requeued (a drain's deadline preemption:
                # the job lives on elsewhere, THIS pod must die).
                cancels.append({"run_id": rid, "job_id": job.id})
        # The jobs' submit trace contexts, batched (one ledger lock for
        # the whole reply): the agent echoes each lease's traceparent on
        # that run's lifecycle reports so run events join the job's
        # trace (JSON wire only; the proto LeaseResponse drops it).
        timeline = getattr(self.scheduler, "timeline", None)
        if timeline is not None and leases:
            tps = timeline.traceparents([lease["job_id"] for lease in leases])
            for lease in leases:
                lease["traceparent"] = tps[lease["job_id"]]
        fence_of = getattr(self.scheduler, "executor_fence", None)
        config = getattr(self.scheduler, "config", None)
        return {
            "leases": leases,
            "cancel_runs": cancels,
            "active_runs": active,
            # Agents defer creating pods for NEW leases while false;
            # unacked leases are simply re-sent after recovery.
            "store_healthy": store_healthy,
            # Fencing token to echo on the next exchange, and the
            # server-advertised lease TTL the agent arms its partition
            # detector with (see executor_agent.ExecutorAgent).
            "fence_token": fence_of(name) if fence_of is not None else 0,
            "lease_ttl_s": (
                float(config.executor_lease_ttl_s)
                if config is not None
                else 0.0
            ),
        }

    def _report_events(self, req):
        """Executor-side state transitions republished to the log
        (ExecutorApi.ReportEvents, api.go:347). Fenced like the lease
        path: a partitioned executor whose runs were reassigned must not
        land stale terminal reports — the requeued job's NEW run is the
        only one allowed a terminal outcome."""
        self._check_fence(
            "ReportEvents", req.get("executor", ""), req.get("fence_token")
        )
        from ..events import (
            EventSequence,
            JobRunErrors,
            JobRunPending,
            JobRunRunning,
            JobRunSucceeded,
            JobSucceeded,
        )

        type_map = {
            "pending": lambda e: [
                JobRunPending(created=e["created"], job_id=e["job_id"],
                              run_id=e["run_id"])
            ],
            "running": lambda e: [
                JobRunRunning(created=e["created"], job_id=e["job_id"],
                              run_id=e["run_id"])
            ],
            "succeeded": lambda e: [
                JobRunSucceeded(created=e["created"], job_id=e["job_id"],
                                run_id=e["run_id"]),
                JobSucceeded(created=e["created"], job_id=e["job_id"]),
            ],
            "failed": lambda e: [
                JobRunErrors(created=e["created"], job_id=e["job_id"],
                             run_id=e["run_id"], error=e.get("error", ""),
                             retryable=bool(e.get("retryable", True)),
                             debug=e.get("debug", "")),
            ],
        }
        items = req.get("events", [])
        # Validate the whole batch before publishing anything: a malformed
        # item must not leave a half-published batch that a client retry
        # would duplicate into the durable log.
        for item in items:
            if item.get("type") not in type_map:
                raise ValueError(f"unknown event type {item.get('type')!r}")
            for key in ("job_id", "run_id", "queue", "jobset", "created"):
                if key not in item:
                    raise ValueError(f"event missing field {key!r}: {item}")
        for item in items:
            events = type_map[item["type"]](item)
            self.log.publish(
                EventSequence.of(
                    item["queue"], item["jobset"], *events,
                    # Run reports re-join the job's trace: the agent
                    # echoes the traceparent its lease carried.
                    traceparent=item.get("traceparent", ""),
                )
            )
        return {}

    def _executor_sync(self, req):
        """Anti-entropy full-state sync (post-partition reconciliation).

        The executor reports EVERY pod it actually holds; the server
        diffs that set against the jobdb and classifies each side's
        surplus deterministically:

          zombie     the pod's run is unknown, its job already terminal,
                     or its job was requeued after lease expiry — tear
                     the pod down; its outcome must never land
          duplicate  the run was superseded by a newer run of the same
                     job (requeue + re-lease won the race) — tear the
                     old pod down so exactly one attempt survives
          kept       still this executor's latest live run — re-adopted
          orphaned   the jobdb holds a live run here that the executor
                     no longer has — failed retryable (requeue path),
                     the missing-pod reconciliation made explicit

        The reply carries the executor's CURRENT fence token: completing
        a sync is the one way a fenced executor rejoins the lease flow.
        """
        from ..events import EventSequence, JobRunErrors

        name = req["executor"]
        runs = req.get("runs", [])
        txn = self.scheduler.jobdb.read_txn()
        agent_runs = {r["run_id"] for r in runs}
        kill, kept, orphaned = [], [], []
        resolutions = {"zombie": 0, "duplicate": 0, "kept": 0, "orphaned": 0}

        def _kill(rid, job_id, reason, kind):
            kill.append({"run_id": rid, "job_id": job_id, "reason": reason})
            resolutions[kind] += 1

        for r in runs:
            rid = r["run_id"]
            job = txn.job_for_any_run(rid)
            if job is None:
                _kill(rid, r.get("job_id", ""), "unknown run", "zombie")
            elif job.state == JobState.QUEUED:
                # Requeued after expiry, new run not yet leased: the old
                # pod is fenced out — the re-lease must start clean.
                _kill(rid, job.id, "job requeued after lease expiry",
                      "zombie")
            elif job.state.terminal:
                _kill(rid, job.id, f"job already {job.state.value}",
                      "zombie")
            elif (
                job.latest_run is None
                or job.latest_run.id != rid
                or job.latest_run.executor != name
            ):
                _kill(rid, job.id, "superseded by a newer run", "duplicate")
            else:
                kept.append(rid)
                resolutions["kept"] += 1
        import time as _t

        now = _t.time()
        for job in txn.jobs_for_executor(name):
            run = job.latest_run
            if run is None or run.id in agent_runs:
                continue
            if job.state not in (JobState.PENDING, JobState.RUNNING):
                # LEASED runs re-send through the normal lease path.
                continue
            orphaned.append(run.id)
            resolutions["orphaned"] += 1
            self.log.publish(
                EventSequence.of(
                    job.queue,
                    job.jobset,
                    JobRunErrors(
                        created=now,
                        job_id=job.id,
                        run_id=run.id,
                        error=(
                            "pod missing on executor after partition "
                            "(anti-entropy sync)"
                        ),
                        retryable=True,
                    ),
                )
            )
        fence_of = getattr(self.scheduler, "executor_fence", None)
        fence = fence_of(name) if fence_of is not None else 0
        synced = getattr(self.scheduler, "note_executor_synced", None)
        if synced is not None:
            synced(name)
        metrics = getattr(self.scheduler, "metrics", None)
        if metrics is not None and metrics.registry is not None:
            for kind, count in resolutions.items():
                if count:
                    metrics.anti_entropy_resolutions.labels(
                        resolution=kind
                    ).inc(count)
        return {
            "fence_token": fence,
            "kill_runs": kill,
            "kept_run_ids": kept,
            "orphaned_run_ids": orphaned,
        }

    def _get_logs(self, req):
        if self.binoculars is None:
            raise KeyError("binoculars not enabled")
        return {
            "lines": self.binoculars.get_logs(
                req["job_id"], int(req.get("tail_lines", 100))
            )
        }

    def _cordon_node(self, req):
        if self.binoculars is None:
            raise KeyError("binoculars not enabled")
        if req.get("uncordon"):
            self.binoculars.uncordon_node(req["node_id"])
        else:
            self.binoculars.cordon_node(req["node_id"])
        return {}

    # ---- streaming ----

    def _watch_entries(self, queue, jobset, cursor, watch, context):
        """Shared watch core: (offset, EventSequence) pairs for one jobset,
        following the log when `watch`. Both wire encodings stream through
        this, so cursor/index semantics cannot diverge."""
        cond = self.log.watcher() if watch else None
        try:
            while context.is_active():
                batch = None
                if self.event_index is not None:
                    # Per-jobset stream read (eventstore.go:24-46): the
                    # index has already partitioned the log, so this
                    # watcher touches only its jobset's entries. Sync here
                    # keeps the view current even between scheduler cycles.
                    self.event_index.sync()
                    batch = self.event_index.read_from(
                        queue, jobset, cursor, 1000
                    )
                if batch is not None:
                    # Index path: the cursor advances only over this
                    # jobset's own offsets.
                    if batch:
                        cursor = batch[-1][0] + 1
                else:
                    # No index, or the jobset aged out of it (retention):
                    # the log is the source of truth, scan it directly.
                    # The cursor advances past every scanned entry,
                    # matching or not — never rewound to the last match.
                    batch = []
                    cursor = max(cursor, self.log.start_offset)
                    from ..events.file_log import CompactedLogError

                    try:
                        entries = self.log.read(cursor, 1000)
                    except CompactedLogError:
                        # A concurrent compact() advanced start_offset
                        # between the clamp and the read — skip the
                        # compacted history and retry rather than aborting
                        # the watch stream.
                        continue
                    for entry in entries:
                        cursor = entry.offset + 1
                        seq = entry.sequence
                        if seq.queue == queue and seq.jobset == jobset:
                            batch.append((entry.offset, seq))
                yield from batch
                if not watch:
                    return
                with cond:
                    cond.wait(timeout=0.5)
        finally:
            if cond is not None:
                self.log.remove_watcher(cond)

    def _watch_jobset(self, req, context):
        """Server-streaming jobset events (event.proto:279 GetJobSetEvents)."""
        for offset, seq in self._watch_entries(
            req["queue"],
            req["jobset"],
            int(req.get("from_offset", 0)),
            bool(req.get("watch", True)),
            context,
        ):
            for event in seq.events:
                payload = {
                    "type": type(event).__name__,
                    "offset": offset,
                    **{
                        k: v
                        for k, v in dataclasses.asdict(event).items()
                        if k != "job" and not isinstance(v, dict)
                    },
                }
                if hasattr(event, "job") and event.job is not None:
                    payload["job_id"] = event.job.id
                yield _encode(payload)

    # ---- wiring ----

    def _proto_handler(self, method: str, table, gate, watchers):
        """RPC handler for the binary-protobuf service: proto request ->
        json_format dict -> the SAME method handler -> proto response.
        Field names in proto/armada.proto match the JSON wire, so the two
        encodings cannot drift. WatchJobSet streams full EventSequenceEntry
        messages (the armadaevents EventSequence shape) straight from the
        log entries."""
        from google.protobuf import json_format

        from ..proto import armada_pb2 as pb

        unary_types = {
            "SubmitJobs": (pb.JobSubmitRequest, pb.JobSubmitResponse),
            "CancelJobs": (pb.JobCancelRequest, pb.JobCancelResponse),
            "ReprioritizeJobs": (
                pb.JobReprioritizeRequest,
                pb.JobReprioritizeResponse,
            ),
            # Executor wire (executorapi.proto role): transforms adapt the
            # nested proto map/bytes shapes to the JSON handler's layout.
            "ExecutorLease": (pb.LeaseRequest, pb.LeaseResponse),
            "ReportEvents": (pb.ReportEventsRequest, pb.ReportEventsResponse),
            "ExecutorSync": (
                pb.ExecutorSyncRequest,
                pb.ExecutorSyncResponse,
            ),
            # What-if planner (armada_tpu/whatif): structured plans and
            # drain statuses travel as JSON strings on this wire.
            "WhatIf": (pb.WhatIfRequest, pb.WhatIfResponse),
            "PlanDrain": (pb.PlanDrainRequest, pb.PlanDrainResponse),
            "ExecuteDrain": (
                pb.ExecuteDrainRequest,
                pb.ExecuteDrainResponse,
            ),
        }
        req_transforms = {
            "ExecutorLease": _lease_req_from_proto_dict,
            # proto3 cannot distinguish unset from zero: a zero-valued
            # deadline/rounds/solver from MessageToDict means "default"
            # on this wire (the JSON wire keeps explicit 0 semantics).
            "WhatIf": _whatif_req_from_proto_dict,
            "PlanDrain": _whatif_req_from_proto_dict,
            "ExecuteDrain": _whatif_req_from_proto_dict,
        }
        resp_transforms = {
            "ExecutorLease": _lease_resp_to_proto_dict,
            "WhatIf": _plan_resp_to_proto_dict,
            "PlanDrain": _plan_resp_to_proto_dict,
            "ExecuteDrain": _status_resp_to_proto_dict,
        }
        if method == "WatchJobSet":
            def stream(request, context):
                msg = pb.WatchRequest.FromString(request)
                req = {
                    "queue": msg.queue,
                    "jobset": msg.jobset,
                    "from_offset": int(msg.from_offset),
                    "watch": bool(msg.follow),
                }
                gate(method, req, context)
                # Same watcher bound as the JSON stream: parked watch
                # threads must not starve unary RPCs of the pool.
                if not watchers.acquire(blocking=False):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        "too many concurrent watchers",
                    )
                try:
                    yield from self._watch_jobset_proto(msg, context)
                finally:
                    watchers.release()

            return grpc.unary_stream_rpc_method_handler(
                stream,
                request_deserializer=bytes,
                response_serializer=lambda m: m.SerializeToString(),
            )
        if method not in unary_types:
            return None
        req_type, resp_type = unary_types[method]
        fn = table.get(method)

        def unary(request, context):
            msg = req_type.FromString(request)
            # Defaults included: proto3 omits zero-valued fields from
            # MessageToDict otherwise, and e.g. ReprioritizeJobs to
            # priority 0 must look identical to the JSON encoding.
            req = json_format.MessageToDict(
                msg,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            )
            req_tf = req_transforms.get(method)
            if req_tf is not None:
                req = req_tf(req)
            gate(method, req, context)
            from ..whatif.planner import WhatIfBusyError
            from .chaos import CircuitOpenError

            token = _call_deadline(context)
            with _rpc_span(method, context):
                try:
                    out = fn(req) or {}
                except KeyError as e:
                    context.abort(grpc.StatusCode.NOT_FOUND, str(e))
                except ValueError as e:
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                except CircuitOpenError as e:
                    context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
                except AdmissionError as e:
                    # Shed with a machine-readable retry hint: clients
                    # back off deliberately instead of timing out.
                    context.set_trailing_metadata(
                        ((RETRY_AFTER_KEY, f"{e.retry_after_s:.3f}"),)
                    )
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, str(e)
                    )
                except DeadlineExpired as e:
                    context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED, str(e)
                    )
                except WhatIfBusyError as e:
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, str(e)
                    )
                except FencedError as e:
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION, str(e)
                    )
                finally:
                    if token is not None:
                        _CALL_DEADLINE.reset(token)
            resp_tf = resp_transforms.get(method)
            if resp_tf is not None:
                out = resp_tf(out)
            resp = resp_type()
            json_format.ParseDict(out, resp, ignore_unknown_fields=True)
            return resp

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=bytes,
            response_serializer=lambda m: m.SerializeToString(),
        )

    def _watch_jobset_proto(self, msg, context):
        """Proto watch: one EventSequenceEntry per matching log entry
        (the armadaevents EventSequence shape), over the shared core."""
        from ..proto import sequence_to_proto

        for offset, seq in self._watch_entries(
            msg.queue, msg.jobset, int(msg.from_offset), bool(msg.follow),
            context,
        ):
            yield sequence_to_proto(offset, seq)

    def method_table(self):
        return {
            "SubmitJobs": self._submit_jobs,
            "CancelJobs": self._cancel_jobs,
            "ReprioritizeJobs": self._reprioritize,
            "CreateQueue": self._create_queue,
            "UpdateQueue": self._update_queue,
            "DeleteQueue": self._delete_queue,
            "GetQueue": self._get_queue,
            "ListQueues": self._list_queues,
            "GetJobs": self._get_jobs,
            "GroupJobs": self._group_jobs,
            "SchedulingReport": self._scheduling_report,
            "QueueReport": self._queue_report,
            "JobReport": self._job_report,
            "JobTrace": self._job_trace,
            "SLOStatus": self._slo_status,
            "Doctor": self._doctor,
            "FairnessReport": self._fairness_report,
            "GetJobLogs": self._get_logs,
            "CordonNode": self._cordon_node,
            "SetPriorityOverride": self._set_priority_override,
            "ListPriorityOverrides": self._list_priority_overrides,
            "PolicyShow": self._policy_show,
            "PolicySet": self._policy_set,
            "ExecutorLease": self._executor_lease,
            "ReportEvents": self._report_events,
            "ExecutorSync": self._executor_sync,
            "CordonExecutor": self._cordon_executor,
            "WhatIf": self._what_if,
            "PlanDrain": self._plan_drain,
            "ExecuteDrain": self._execute_drain,
        }

    def serve(self, port: int = 0, max_workers: int = 16, max_watchers: int | None = None,
              tls: tuple | None = None):
        """Serve on 127.0.0.1:port; `tls=(cert_file, key_file)` serves TLS
        (grpc ssl_server_credentials — the reference's
        internal/common/grpc TLS listener config).

        Watch streams park a worker thread each in a wait loop; unbounded
        watchers would starve unary RPCs (executor lease exchanges) of the
        shared pool. `max_watchers` (default: max_workers - 4) bounds them
        so unary handlers always have threads; excess watchers are rejected
        with RESOURCE_EXHAUSTED and may retry."""
        import threading
        from concurrent import futures

        if max_watchers is None:
            max_watchers = max(1, max_workers - 4)
        table = self.method_table()
        outer = self
        watchers = threading.Semaphore(max_watchers)

        from .auth import AuthError, PermissionDenied

        def gate(method, request, context):
            """Authenticate + authorize one call; aborts on failure."""
            if outer.auth is None:
                return None
            md = {
                k.lower(): v for k, v in (context.invocation_metadata() or ())
            }
            try:
                principal = outer.auth.authenticate(md)
                outer._authorize(method, principal, request)
                return principal
            except AuthError as e:
                context.abort(grpc.StatusCode.UNAUTHENTICATED, str(e))
            except PermissionDenied as e:
                context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method  # /Service/Method
                parts = name.strip("/").split("/")
                if len(parts) != 2:
                    return None
                if parts[0] == PROTO_SERVICE:
                    # Binary protobuf encoding of the same methods
                    # (proto/armada.proto; the reference's pkg/api protos).
                    return outer._proto_handler(parts[1], table, gate, watchers)
                if parts[0] != SERVICE:
                    return None
                method = parts[1]
                if method == "WatchJobSet":
                    def stream(request, context):
                        req = _decode(request)
                        gate(method, req, context)
                        if not watchers.acquire(blocking=False):
                            context.abort(
                                grpc.StatusCode.RESOURCE_EXHAUSTED,
                                f"too many concurrent watchers (max {max_watchers})",
                            )
                        try:
                            yield from outer._watch_jobset(req, context)
                        finally:
                            watchers.release()

                    return grpc.unary_stream_rpc_method_handler(
                        stream,
                        request_deserializer=bytes,
                        response_serializer=bytes,
                    )
                fn = table.get(method)
                if fn is None:
                    return None

                def unary(request, context):
                    from ..whatif.planner import WhatIfBusyError
                    from .chaos import CircuitOpenError

                    req = _decode(request)
                    gate(method, req, context)
                    token = _call_deadline(context)
                    with _rpc_span(method, context):
                        try:
                            return _encode(fn(req))
                        except KeyError as e:
                            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
                        except ValueError as e:
                            context.abort(
                                grpc.StatusCode.INVALID_ARGUMENT, str(e)
                            )
                        except CircuitOpenError as e:
                            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
                        except AdmissionError as e:
                            context.set_trailing_metadata(
                                ((RETRY_AFTER_KEY,
                                  f"{e.retry_after_s:.3f}"),)
                            )
                            context.abort(
                                grpc.StatusCode.RESOURCE_EXHAUSTED, str(e)
                            )
                        except DeadlineExpired as e:
                            context.abort(
                                grpc.StatusCode.DEADLINE_EXCEEDED, str(e)
                            )
                        except WhatIfBusyError as e:
                            context.abort(
                                grpc.StatusCode.RESOURCE_EXHAUSTED, str(e)
                            )
                        except FencedError as e:
                            context.abort(
                                grpc.StatusCode.FAILED_PRECONDITION, str(e)
                            )
                        finally:
                            if token is not None:
                                _CALL_DEADLINE.reset(token)

                return grpc.unary_unary_rpc_method_handler(
                    unary, request_deserializer=bytes, response_serializer=bytes
                )

        server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        server.add_generic_rpc_handlers((Handler(),))
        if tls is not None:
            cert_file, key_file = tls
            with open(cert_file, "rb") as f:
                cert = f.read()
            with open(key_file, "rb") as f:
                key = f.read()
            creds = grpc.ssl_server_credentials(((key, cert),))
            bound_port = server.add_secure_port(f"127.0.0.1:{port}", creds)
        else:
            bound_port = server.add_insecure_port(f"127.0.0.1:{port}")
        server.start()
        return server, bound_port


# Channel options for clients that must notice a healed partition
# promptly. gRPC's default reconnect backoff grows to 120s: a few severed
# connection attempts during a short partition push the next connect out
# by minutes, during which every RPC fails fast on the cached error while
# the wire is actually fine (found by the netchaos drive). An executor
# agent's whole partition protocol (lease TTL, fence recovery) assumes
# reconnection is attempted within seconds of the heal.
CHANNEL_OPTIONS = (
    ("grpc.min_reconnect_backoff_ms", 200),
    ("grpc.max_reconnect_backoff_ms", 5000),
    ("grpc.keepalive_time_ms", 30000),
    ("grpc.keepalive_timeout_ms", 10000),
)


def _retrying_call(invoke, retry_budget_s: float, seed: int = 0):
    """Shared client retry loop: a RESOURCE_EXHAUSTED reply carrying the
    server's `retry-after` trailing metadata (front-door shedding) is
    retried after max(server hint, jittered exponential delay), with the
    CUMULATIVE sleep capped by `retry_budget_s` — the executor-agent
    lease path's bounded-backoff discipline applied to submit clients.
    Every other failure (other codes, or exhaustion without a hint, e.g.
    a full what-if backlog) raises immediately, as before."""
    from .chaos import ExponentialBackoff

    backoff = None
    while True:
        try:
            return invoke()
        except grpc.RpcError as e:
            retry_after = _retry_after_of(e)
            if retry_after is None or retry_budget_s <= 0:
                raise
            if backoff is None:
                backoff = ExponentialBackoff(
                    base_s=0.05, cap_s=5.0, seed=seed,
                    budget_s=retry_budget_s,
                )
            if backoff.exhausted:
                raise
            jitter = backoff.next_delay()
            # Clamp the server hint to the REMAINING budget (not the
            # whole budget) so cumulative sleep stays <= retry_budget_s.
            remaining = max(0.0, retry_budget_s - backoff.spent_s)
            delay = max(jitter, min(retry_after, remaining))
            # The server hint may exceed the jittered delay; charge the
            # surplus against the budget so the streak stays bounded.
            backoff.spent_s += max(0.0, delay - jitter)
            if delay > 0:
                _time.sleep(delay)


class ApiClient:
    """Python client for the gRPC API (pkg/client + client/python analogue).

    Credentials: pass `token=` (Bearer JWT) or `basic=(user, password)` —
    the client attaches the authorization metadata the server's auth chain
    expects (client/rust/src/auth.rs plays the same role).

    Shed responses (RESOURCE_EXHAUSTED with the server's `retry-after`
    hint) are retried with a bounded, jittered backoff; `retry_budget_s`
    caps the cumulative sleep per call (0 disables retries)."""

    def __init__(self, target: str, token: str | None = None, basic=None,
                 ca_cert: str | None = None, retry_budget_s: float = 30.0,
                 retry_seed: int = 0):
        options = list(CHANNEL_OPTIONS)
        if ca_cert:
            with open(ca_cert, "rb") as f:
                creds = grpc.ssl_channel_credentials(root_certificates=f.read())
            self.channel = grpc.secure_channel(target, creds, options=options)
        else:
            self.channel = grpc.insecure_channel(target, options=options)
        self.retry_budget_s = retry_budget_s
        self._retry_seed = retry_seed
        self._metadata: list = []
        if token:
            self._metadata = [("authorization", f"Bearer {token}")]
        elif basic:
            import base64

            user, password = basic
            cred = base64.b64encode(f"{user}:{password}".encode()).decode()
            self._metadata = [("authorization", f"Basic {cred}")]

    def _call(self, method: str, request: dict, timeout: float | None = None):
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=bytes,
            response_deserializer=bytes,
        )

        def invoke():
            return _decode(
                fn(
                    _encode(request),
                    metadata=_inject_traceparent(self._metadata) or None,
                    timeout=timeout,
                )
            )

        return _retrying_call(
            invoke, self.retry_budget_s, seed=self._retry_seed
        )

    def submit_jobs(self, queue, jobset, jobs: list[dict],
                    deadline_s: float | None = None):
        """`deadline_s` sets a gRPC deadline on the call; the server
        propagates it through the admission gate and the ingest enqueue
        (expired work is dropped early, never half-applied)."""
        return self._call(
            "SubmitJobs", {"queue": queue, "jobset": jobset, "jobs": jobs},
            timeout=deadline_s,
        )["job_ids"]

    def cancel_jobs(self, queue, jobset, job_ids=(), cancel_jobset=False, reason=""):
        self._call(
            "CancelJobs",
            {
                "queue": queue,
                "jobset": jobset,
                "job_ids": list(job_ids),
                "cancel_jobset": cancel_jobset,
                "reason": reason,
            },
        )

    def reprioritize_jobs(self, queue, jobset, job_ids, priority):
        self._call(
            "ReprioritizeJobs",
            {
                "queue": queue,
                "jobset": jobset,
                "job_ids": list(job_ids),
                "priority": priority,
            },
        )

    def create_queue(self, name, priority_factor=1.0, cordoned=False):
        self._call(
            "CreateQueue",
            {"name": name, "priority_factor": priority_factor, "cordoned": cordoned},
        )

    def update_queue(self, name, priority_factor=None, cordoned=None):
        self._call(
            "UpdateQueue",
            {"name": name, "priority_factor": priority_factor, "cordoned": cordoned},
        )

    def delete_queue(self, name):
        self._call("DeleteQueue", {"name": name})

    def get_queue(self, name):
        return self._call("GetQueue", {"name": name})

    def list_queues(self):
        return self._call("ListQueues", {})["queues"]

    def get_jobs(self, filters=(), order_field="submitted", order_direction="asc",
                 skip=0, take=100):
        return self._call(
            "GetJobs",
            {
                "filters": list(filters),
                "order_field": order_field,
                "order_direction": order_direction,
                "skip": skip,
                "take": take,
            },
        )

    def group_jobs(self, group_by, filters=(), aggregates=()):
        return self._call(
            "GroupJobs",
            {"group_by": group_by, "filters": list(filters),
             "aggregates": list(aggregates)},
        )["groups"]

    def scheduling_report(self):
        return self._call("SchedulingReport", {})["report"]

    def queue_report(self, queue):
        return self._call("QueueReport", {"queue": queue})["report"]

    def job_report(self, job_id):
        return self._call("JobReport", {"job_id": job_id})["report"]

    def slo_status(self):
        """Declared SLOs + compliance + burn rates (services/slo.py)."""
        return self._call("SLOStatus", {})

    def doctor(self):
        """Self-healing-solve state: failover ladder breaker states,
        recent round rejections (+ quarantine bundle paths), recent
        failovers (scheduler.doctor_report; GET /api/doctor serves the
        same)."""
        return self._call("Doctor", {})

    def fairness_report(self, pool=None):
        """Fairness observatory document: {"pools": {pool: {ledger,
        preemptions, alerts...}}, "alerts": [...]}
        (observe/fairness.py; GET /api/fairness serves the same)."""
        return self._call("FairnessReport", {"pool": pool or ""})

    def job_trace(self, job_id):
        """The job's end-to-end journey: {"journey": <dict>, "rendered":
        <text timeline>} (services/job_timeline.py)."""
        return self._call("JobTrace", {"job_id": job_id})

    def set_priority_override(self, queue, priority_factor):
        self._call(
            "SetPriorityOverride",
            {"queue": queue, "priority_factor": priority_factor},
        )

    def list_priority_overrides(self):
        return self._call("ListPriorityOverrides", {})["overrides"]

    def policy_show(self, pool=None):
        """Active fairness policy per pool: {"default", "overrides",
        "pools": {pool: policy}} (solver/policy.py)."""
        return self._call("PolicyShow", {"pool": pool or ""})

    def policy_set(self, pool, policy, force=False, scorecard=None):
        """Flip (policy string) or clear (policy None/"") a pool's
        fairness policy. Non-DRF flips need a registered shadow
        scorecard unless force=True (the divergence gate)."""
        return self._call(
            "PolicySet",
            {
                "pool": pool,
                "policy": policy or "",
                "force": bool(force),
                "scorecard": scorecard,
            },
        )

    def get_job_logs(self, job_id, tail_lines=100):
        return self._call("GetJobLogs", {"job_id": job_id, "tail_lines": tail_lines})[
            "lines"
        ]

    def what_if(self, mutations, pool=None, solver=None, rounds=None):
        """Shadow-solve hypothetical edits against the live round fork:
        {"plan": <structured plan>, "rendered": <text>}. `mutations` is
        a list of {"kind": ..., ...} dicts (whatif/mutations.py)."""
        return self._call(
            "WhatIf",
            {
                "mutations": list(mutations),
                "pool": pool or "",
                "solver": solver or "",
                "rounds": rounds or 0,
            },
        )

    def plan_drain(self, executor, pool=None, solver=None, rounds=None,
                   deadline_s=None):
        return self._call(
            "PlanDrain",
            {
                "executor": executor,
                "pool": pool or "",
                "solver": solver or "",
                "rounds": rounds or 0,
                "deadline_s": deadline_s,
            },
        )

    def execute_drain(self, executor, deadline_s=None, status_only=False):
        return self._call(
            "ExecuteDrain",
            {
                "executor": executor,
                "deadline_s": deadline_s,
                "status_only": bool(status_only),
            },
        )["status"]

    def cordon_node(self, node_id, uncordon=False):
        self._call("CordonNode", {"node_id": node_id, "uncordon": uncordon})

    def cordon_executor(self, executor, uncordon=False):
        self._call(
            "CordonExecutor", {"executor": executor, "uncordon": uncordon}
        )

    def watch_jobset(self, queue, jobset, from_offset=0, watch=True):
        fn = self.channel.unary_stream(
            f"/{SERVICE}/WatchJobSet",
            request_serializer=bytes,
            response_deserializer=bytes,
        )
        stream = fn(
            _encode(
                {"queue": queue, "jobset": jobset, "from_offset": from_offset,
                 "watch": watch}
            ),
            metadata=_inject_traceparent(self._metadata) or None,
        )
        for msg in stream:
            yield _decode(msg)


def connect(target: str, ca_cert: str | None = None,
            token: str | None = None) -> ApiClient:
    """Env-aware client factory (pkg/client ApiConnectionDetails
    analogue): TLS when a CA bundle is given (flag or ARMADA_CA_CERT),
    Bearer token from ARMADA_TOKEN when present — the client-side half
    of the server's TLS + auth chain (client/rust/src/auth.rs role)."""
    import os

    ca_cert = ca_cert or os.environ.get("ARMADA_CA_CERT") or None
    token = token or os.environ.get("ARMADA_TOKEN") or None
    return ApiClient(target, ca_cert=ca_cert, token=token)


class ProtoApiClient:
    """Binary-protobuf client over proto/armada.proto — what a codegen
    client in any protobuf language looks like against this server (the
    reference's generated pkg/api clients). Python builds it from the
    same generated armada_pb2 the server uses."""

    def __init__(self, target: str, token: str | None = None, basic=None,
                 ca_cert: str | None = None, retry_budget_s: float = 30.0,
                 retry_seed: int = 0):
        options = list(CHANNEL_OPTIONS)
        if ca_cert:
            with open(ca_cert, "rb") as f:
                creds = grpc.ssl_channel_credentials(root_certificates=f.read())
            self.channel = grpc.secure_channel(target, creds, options=options)
        else:
            self.channel = grpc.insecure_channel(target, options=options)
        # Shed responses retry like ApiClient: bounded jittered backoff
        # honoring the server's retry-after hint.
        self.retry_budget_s = retry_budget_s
        self._retry_seed = retry_seed
        # Same credential surface as ApiClient: Bearer or Basic metadata
        # for the server's auth chain.
        self._metadata: list = []
        if token:
            self._metadata = [("authorization", f"Bearer {token}")]
        elif basic:
            import base64

            user, password = basic
            cred = base64.b64encode(f"{user}:{password}".encode()).decode()
            self._metadata = [("authorization", f"Basic {cred}")]

    def _unary(self, method: str, request, resp_type,
               timeout: float | None = None):
        fn = self.channel.unary_unary(
            f"/{PROTO_SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_type.FromString,
        )

        def invoke():
            return fn(
                request,
                metadata=_inject_traceparent(self._metadata) or None,
                timeout=timeout,
            )

        return _retrying_call(
            invoke, self.retry_budget_s, seed=self._retry_seed
        )

    def submit_jobs(self, queue: str, jobset: str, items) -> list[str]:
        from ..proto import armada_pb2 as pb

        req = pb.JobSubmitRequest(queue=queue, jobset=jobset)
        for item in items:
            req.jobs.append(item)
        return list(
            self._unary("SubmitJobs", req, pb.JobSubmitResponse).job_ids
        )

    def cancel_jobs(self, queue, jobset, job_ids=(), cancel_jobset=False,
                    reason=""):
        from ..proto import armada_pb2 as pb

        self._unary(
            "CancelJobs",
            pb.JobCancelRequest(
                queue=queue, jobset=jobset, job_ids=list(job_ids),
                cancel_jobset=cancel_jobset, reason=reason,
            ),
            pb.JobCancelResponse,
        )

    def reprioritize_jobs(self, queue, jobset, job_ids, priority):
        from ..proto import armada_pb2 as pb

        self._unary(
            "ReprioritizeJobs",
            pb.JobReprioritizeRequest(
                queue=queue, jobset=jobset, job_ids=list(job_ids),
                priority=priority,
            ),
            pb.JobReprioritizeResponse,
        )

    @staticmethod
    def _whatif_mutation_fields(m: dict) -> dict:
        """JSON-vocabulary mutation dict -> WhatIfMutation field kwargs.
        The proto message carries cpu/memory/gpu scalars instead of the
        JSON wire's `requests` map; translate the common keys and refuse
        anything the binary wire cannot express."""
        m = dict(m)
        requests = m.pop("requests", None) or {}
        scalar_of = {"cpu": "cpu", "memory": "memory", "nvidia.com/gpu": "gpu"}
        for key, value in requests.items():
            field = scalar_of.get(key)
            if field is None:
                raise ValueError(
                    f"the proto wire cannot express request {key!r}; use "
                    "the JSON wire (ApiClient.what_if) for arbitrary "
                    "resource maps"
                )
            m.setdefault(field, str(value))
        for key in ("node_selector", "labels"):
            if m.pop(key, None):
                raise ValueError(
                    f"the proto wire cannot express {key!r}; use the JSON "
                    "wire (ApiClient.what_if)"
                )
        return m

    def what_if(self, mutations, pool="", solver="", rounds=0) -> dict:
        """WhatIf over the binary wire; returns the decoded plan dict
        (the JSON wire's {"plan", "rendered"} shape)."""
        from ..proto import armada_pb2 as pb

        req = pb.WhatIfRequest(pool=pool, solver=solver, rounds=rounds)
        for m in mutations:
            req.mutations.add(**self._whatif_mutation_fields(m))
        resp = self._unary("WhatIf", req, pb.WhatIfResponse)
        return {
            "plan": json.loads(resp.plan_json) if resp.plan_json else {},
            "rendered": resp.rendered,
        }

    def plan_drain(self, executor, pool="", solver="", rounds=0,
                   deadline_s=0.0) -> dict:
        from ..proto import armada_pb2 as pb

        resp = self._unary(
            "PlanDrain",
            pb.PlanDrainRequest(
                executor=executor, pool=pool, solver=solver, rounds=rounds,
                deadline_s=deadline_s,
            ),
            pb.PlanDrainResponse,
        )
        return {
            "plan": json.loads(resp.plan_json) if resp.plan_json else {},
            "rendered": resp.rendered,
        }

    def execute_drain(self, executor, deadline_s=0.0,
                      status_only=False) -> dict:
        from ..proto import armada_pb2 as pb

        resp = self._unary(
            "ExecuteDrain",
            pb.ExecuteDrainRequest(
                executor=executor, deadline_s=deadline_s,
                status_only=status_only,
            ),
            pb.ExecuteDrainResponse,
        )
        return json.loads(resp.status_json) if resp.status_json else {}

    def watch_jobset(self, queue, jobset, from_offset=0, follow=True):
        """Yields (offset, events.model.EventSequence)."""
        from ..proto import armada_pb2 as pb, sequence_from_proto

        fn = self.channel.unary_stream(
            f"/{PROTO_SERVICE}/WatchJobSet",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.EventSequenceEntry.FromString,
        )
        stream = fn(
            pb.WatchRequest(
                queue=queue, jobset=jobset, from_offset=from_offset,
                follow=follow,
            ),
            metadata=self._metadata or None,
        )
        for entry in stream:
            yield sequence_from_proto(entry)
