"""Submission API: validate, default, deduplicate, publish.

The in-process equivalent of the reference's submit server
(/root/reference/internal/server/submit/submit.go): SubmitJobs validates and
defaults each job, deduplicates by (queue, deduplication_id), converts to
SubmitJob events and publishes them to the event log; cancel/reprioritise
publish the corresponding jobset events. gRPC/REST transport wraps this
object in services/grpc_api.py.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field

from ..core.config import SchedulingConfig
from ..core.types import JobSpec, QueueSpec
from ..events import (
    CancelJob,
    CancelJobSet,
    EventSequence,
    QueueDelete,
    QueueUpsert,
    ReprioritiseJob,
    SubmitJob,
)
from ..events.model import new_id

# Jobset key under which control-plane (queue CRUD) events are logged,
# mirroring the reference's separate controlPlaneEvents topic.
from ..events.model import CONTROL_PLANE_JOBSET  # noqa: F401 (re-export)


class SubmissionError(ValueError):
    pass


@dataclass
class Queue:
    """Control-plane queue record (pkg/client queue API). Owners and
    permission grants feed the transport's Authorizer
    (services/auth.py; permissions.go + queue permission subjects)."""

    spec: QueueSpec
    cordoned: bool = False
    labels: dict = field(default_factory=dict)
    owners: tuple = ()
    permissions: tuple = ()  # of auth.QueuePermission


class SubmitService:
    def __init__(self, config: SchedulingConfig, log, scheduler=None,
                 checkpoint=None, store_health=None, frontdoor=None,
                 slo=None):
        self.config = config
        self.log = log
        self.scheduler = scheduler  # optional: queue updates pushed through
        # Optional SLO tracker (services/slo.py): submit() feeds the
        # frontdoor_submit_seconds signal — wall clock through admission
        # + the durable ack — at the ONE enforcement point every
        # transport funnels through, so gRPC and in-process submits
        # measure identically.
        self.slo = slo
        # Optional backpressure gate (services/backpressure.py): callable
        # -> (healthy, reason); submissions are shed while the store is
        # backed up (the reference rejects work on etcd capacity).
        self.store_health = store_health
        # Optional front door (armada_tpu/frontdoor): job submissions
        # route through per-tenant admission and a jobset-keyed shard WAL
        # (the ack point) instead of publishing straight to the log; the
        # shard ingesters deliver into the log exactly-once. Queue CRUD
        # and cancel/reprioritise stay on the direct path (control-plane
        # volume, not flood surface). When set, the front door's
        # admission owns backpressure shedding (it wraps the same gate),
        # so the raw store_health check above is skipped.
        self.frontdoor = frontdoor
        self.queues: dict[str, Queue] = {}
        self._dedup: dict[tuple, str] = {}  # (queue, dedup_id) -> job_id
        self._cursor = 0  # log offset the view reflects
        if checkpoint is not None:
            # Bounded restart (services/checkpoint.py): seed the registry
            # and dedup index, replay only the suffix.
            self._cursor, state = checkpoint
            self._dedup.update(state["dedup"])
            for queue in state["queues"].values():
                self.queues[queue.spec.name] = queue
                if self.scheduler is not None:
                    self.scheduler.upsert_queue(
                        queue.spec, cordoned=queue.cordoned
                    )
        self._replay()

    def checkpoint_state(self):
        return self._cursor, {
            "queues": dict(self.queues),
            "dedup": dict(self._dedup),
        }

    def _replay(self):
        """Rebuild queue registry and dedup index from the (durable) log —
        the control-plane materialized view (queues in Postgres + dedup
        table in the reference). Starts at the checkpoint cursor (or the
        log's compaction point) and remembers where it stopped; calling it
        again consumes the new suffix (idempotent re-application: local
        mutations were already applied at publish time), which advances
        the checkpoint cursor and, in file-lease HA, picks up queue events
        published by the other replica."""
        self._cursor = max(self._cursor, self.log.start_offset)
        entries = self.log.read(self._cursor, 10**9)
        if entries:
            self._cursor = entries[-1].offset + 1
        for entry in entries:
            for event in entry.sequence.events:
                if isinstance(event, QueueUpsert):
                    from .auth import QueuePermission

                    spec = QueueSpec(event.name, event.priority_factor)
                    perms = tuple(
                        QueuePermission(tuple(p["subjects"]), tuple(p["verbs"]))
                        if isinstance(p, dict)
                        else p
                        for p in getattr(event, "permissions", ())
                    )
                    self.queues[event.name] = Queue(
                        spec=spec,
                        cordoned=event.cordoned,
                        owners=tuple(getattr(event, "owners", ())),
                        permissions=perms,
                    )
                    if self.scheduler is not None:
                        self.scheduler.upsert_queue(spec, cordoned=event.cordoned)
                elif isinstance(event, QueueDelete):
                    self.queues.pop(event.name, None)
                elif isinstance(event, SubmitJob) and event.deduplication_id:
                    self._dedup[
                        (entry.sequence.queue, event.deduplication_id)
                    ] = event.job.id

    def sync(self):
        """Consume the log suffix (see _replay)."""
        self._replay()

    def _publish_queue_event(self, event):
        self.log.publish(EventSequence.of("", CONTROL_PLANE_JOBSET, event))

    # ---- queue CRUD (internal/server/queue) ----

    def create_queue(
        self,
        spec: QueueSpec,
        cordoned: bool = False,
        owners: tuple = (),
        permissions: tuple = (),
    ) -> Queue:
        if spec.name in self.queues:
            raise SubmissionError(f"queue {spec.name!r} already exists")
        q = Queue(
            spec=spec, cordoned=cordoned, owners=tuple(owners),
            permissions=tuple(permissions),
        )
        self.queues[spec.name] = q
        self._publish_queue_event(
            QueueUpsert(
                created=_time.time(),
                name=spec.name,
                priority_factor=spec.priority_factor,
                cordoned=cordoned,
                owners=tuple(owners),
                permissions=tuple(
                    {"subjects": list(p.subjects), "verbs": list(p.verbs)}
                    if not isinstance(p, dict)
                    else p
                    for p in permissions
                ),
            )
        )
        if self.scheduler is not None:
            self.scheduler.upsert_queue(spec, cordoned=cordoned)
        return q

    def update_queue(
        self,
        name: str,
        priority_factor: float | None = None,
        cordoned: bool | None = None,
    ) -> Queue:
        """Partial update: None leaves a field unchanged."""
        q = self.queues.get(name)
        if q is None:
            raise SubmissionError(f"queue {name!r} does not exist")
        if priority_factor is not None:
            q.spec = QueueSpec(name, priority_factor)
        if cordoned is not None:
            q.cordoned = cordoned
        self._publish_queue_event(
            QueueUpsert(
                created=_time.time(),
                name=name,
                priority_factor=q.spec.priority_factor,
                cordoned=q.cordoned,
            )
        )
        if self.scheduler is not None:
            self.scheduler.upsert_queue(q.spec, cordoned=q.cordoned)
        return q

    def delete_queue(self, name: str):
        if name in self.queues:
            self._publish_queue_event(
                QueueDelete(created=_time.time(), name=name)
            )
        self.queues.pop(name, None)

    def get_queue(self, name: str) -> Queue | None:
        return self.queues.get(name)

    # ---- submission (internal/server/submit/submit.go) ----

    def submit(
        self, queue: str, jobset: str, jobs: list[JobSpec],
        now: float | None = None, deadline_ts: float | None = None,
    ) -> list[str]:
        """Validate + publish; returns job ids (existing ids for dedup
        hits). `deadline_ts` is the caller's propagated deadline (same
        clock as `now`): expired work is dropped before the durable
        enqueue — acked work always applies, never half."""
        slo = self.slo
        measure = slo is not None and slo.observes("frontdoor_submit_seconds")
        started = _time.perf_counter() if measure else 0.0
        try:
            return self._submit(queue, jobset, jobs, now, deadline_ts)
        finally:
            if measure:
                # Shed/expired/errored submits count too: a front door
                # that fails fast still spent the user's latency budget.
                slo.observe(
                    "frontdoor_submit_seconds",
                    _time.perf_counter() - started,
                    now=now,
                )

    def _submit(
        self, queue: str, jobset: str, jobs: list[JobSpec],
        now: float | None = None, deadline_ts: float | None = None,
    ) -> list[str]:
        if self.store_health is not None and self.frontdoor is None:
            healthy, reason = self.store_health.check()
            if not healthy:
                raise SubmissionError(f"store backpressure: {reason}")
        if queue not in self.queues:
            raise SubmissionError(f"queue {queue!r} does not exist")
        now = _time.time() if now is None else now
        if self.frontdoor is not None:
            # Per-tenant admission (token buckets + quota-weighted
            # overload shedding) counts JOBS, not RPCs — raises
            # AdmissionError with a retry-after the transport forwards.
            self.frontdoor.admit(queue, len(jobs), now=now)
        self._validate_gangs(jobs)
        events = []
        job_ids = []
        added_dedup = []
        for job in jobs:
            job = self._validate_and_default(queue, jobset, job, now)
            dedup_key = None
            dedup_id = job.annotations.get("armadaproject.io/deduplication-id", "")
            if dedup_id:
                dedup_key = (queue, dedup_id)
                if dedup_key in self._dedup:
                    job_ids.append(self._dedup[dedup_key])
                    continue
            if dedup_key:
                self._dedup[dedup_key] = job.id
                added_dedup.append(dedup_key)
            job_ids.append(job.id)
            events.append(SubmitJob(created=now, job=job, deduplication_id=dedup_id))
        if events:
            # Stamp the caller's trace context (the gRPC server span the
            # transport opened around this handler, utils/tracing.py):
            # the ingester's journey ledger records it per job and the
            # scheduler continues it onto lease events — one trace id
            # from submit RPC through lease.
            from ..utils.tracing import TRACER

            seq = EventSequence.of(
                queue, jobset, *events,
                traceparent=TRACER.current_traceparent(),
            )
            if self.frontdoor is not None:
                # Durable shard-WAL append IS the acknowledgement; the
                # deadline is checked one last time immediately before it
                # (drop early, whole — never a half-applied batch). A
                # dropped batch must not leave phantom dedup entries: a
                # later retry with the same dedup ids has to re-publish.
                try:
                    self.frontdoor.append(
                        seq, deadline_ts=deadline_ts, now=now
                    )
                except Exception:
                    for key in added_dedup:
                        self._dedup.pop(key, None)
                    raise
            else:
                self.log.publish(seq)
        return job_ids

    def _validate_and_default(
        self, queue: str, jobset: str, job: JobSpec, now: float
    ) -> JobSpec:
        """Validation rules from internal/server/submit/validation/."""
        if not job.id:
            job = job.with_(id=new_id("job"))
        job = job.with_(queue=queue, jobset=jobset, submitted_ts=now)
        if not job.requests:
            raise SubmissionError(f"job {job.id}: no resource requests")
        factory = self.config.resource_factory()
        for name in job.requests:
            if name not in factory.name_to_index:
                raise SubmissionError(
                    f"job {job.id}: unsupported resource {name!r}"
                )
        pc_name = job.priority_class or self.config.default_priority_class
        if pc_name not in self.config.priority_classes:
            raise SubmissionError(
                f"job {job.id}: unknown priority class {pc_name!r}"
            )
        job = job.with_(priority_class=pc_name)
        if job.affinity is not None:
            valid_ops = {"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"}
            for term in job.affinity.terms:
                for expr in term.expressions:
                    if expr.operator not in valid_ops:
                        raise SubmissionError(
                            f"job {job.id}: unknown affinity operator "
                            f"{expr.operator!r}"
                        )
        if job.gang is not None:
            if job.gang.cardinality < 1:
                raise SubmissionError(f"job {job.id}: gang cardinality < 1")
        return job

    def _validate_gangs(self, jobs: list[JobSpec]):
        """Gang member agreement (internal/scheduler/gang_validator.go):
        every member of a gang submitted together must declare the same
        cardinality, node-uniformity label and priority class; a batch
        must not carry more members than the declared cardinality."""
        by_gang: dict[str, list[JobSpec]] = {}
        for job in jobs:
            if job.gang is not None and job.gang.id:
                by_gang.setdefault(job.gang.id, []).append(job)
        for gid, members in by_gang.items():
            first = members[0]
            for m in members[1:]:
                if m.gang.cardinality != first.gang.cardinality:
                    raise SubmissionError(
                        f"gang {gid}: members disagree on cardinality "
                        f"({m.gang.cardinality} vs {first.gang.cardinality})"
                    )
                if m.gang.node_uniformity_label != first.gang.node_uniformity_label:
                    raise SubmissionError(
                        f"gang {gid}: members disagree on node uniformity label"
                    )
                if (m.priority_class or "") != (first.priority_class or ""):
                    raise SubmissionError(
                        f"gang {gid}: members disagree on priority class"
                    )
            if len(members) > first.gang.cardinality:
                raise SubmissionError(
                    f"gang {gid}: {len(members)} members exceed declared "
                    f"cardinality {first.gang.cardinality}"
                )

    # ---- cancel / reprioritise ----

    def cancel_job(self, queue: str, jobset: str, job_id: str, reason: str = ""):
        self.log.publish(
            EventSequence.of(
                queue, jobset, CancelJob(created=_time.time(), job_id=job_id, reason=reason)
            )
        )

    def cancel_jobset(self, queue: str, jobset: str, reason: str = ""):
        self.log.publish(
            EventSequence.of(
                queue, jobset, CancelJobSet(created=_time.time(), reason=reason)
            )
        )

    def reprioritise_job(self, queue: str, jobset: str, job_id: str, priority: int):
        self.log.publish(
            EventSequence.of(
                queue,
                jobset,
                ReprioritiseJob(created=_time.time(), job_id=job_id, priority=priority),
            )
        )
