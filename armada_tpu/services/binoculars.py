"""Binoculars: job log access and node cordoning.

The reference runs a per-cluster aux service for these two operations
because the control plane has no kube-api access
(/root/reference/internal/binoculars/server.go:17, service/{logs,cordon}.go).
Here executors expose the same two capabilities through their heartbeat
connection; the control-plane service routes by node/executor. Fake
executors synthesize log lines; a real executor agent would proxy its
container runtime.
"""

from __future__ import annotations


class BinocularsService:
    def __init__(self, scheduler, executors=None):
        self.scheduler = scheduler
        # name -> executor object exposing get_logs/cordon (FakeExecutor or
        # a remote proxy).
        self.executors = {e.name: e for e in (executors or [])}

    def register(self, executor):
        self.executors[executor.name] = executor

    def get_logs(self, job_id: str, tail_lines: int = 100) -> list[str]:
        job = self.scheduler.jobdb.get(job_id)
        if job is None:
            raise KeyError(f"job {job_id} not found")
        run = job.latest_run
        if run is None:
            return []
        executor = self.executors.get(run.executor)
        if executor is None or not hasattr(executor, "get_logs"):
            raise KeyError(f"executor {run.executor!r} not reachable")
        return executor.get_logs(job_id, tail_lines)

    def set_cordon(self, node_id: str, cordoned: bool) -> bool:
        for executor in self.executors.values():
            if hasattr(executor, "cordon") and executor.cordon(node_id, cordoned):
                return True
        raise KeyError(f"node {node_id} not found on any executor")

    def cordon_node(self, node_id: str) -> bool:
        return self.set_cordon(node_id, True)

    def uncordon_node(self, node_id: str) -> bool:
        return self.set_cordon(node_id, False)
