"""Leader election: standalone and file-lease modes.

The reference supports `standalone` (always leader) and `kubernetes`
(coordination.k8s.io Lease) modes, with a LeaderToken whose validity gates
publishing (/root/reference/internal/leaderelection/leaderelection.go:16-63).
Kubernetes is out of scope here; the file-lease mode gives multi-process
HA on a shared filesystem with the same token semantics: a cycle captures a
token at its start, and publishes only validate against that token — losing
leadership mid-cycle invalidates the token so the next leader re-derives
events idempotently (scheduler.go:225-233).
"""

from __future__ import annotations

import os
import time as _time
import uuid
from dataclasses import dataclass


@dataclass(frozen=True)
class LeaderToken:
    leader: bool
    id: str = ""


class StandaloneLeader:
    """Always the leader (leader.mode=standalone)."""

    def __init__(self):
        self._id = str(uuid.uuid4())

    def get_token(self) -> LeaderToken:
        return LeaderToken(leader=True, id=self._id)

    def validate(self, token: LeaderToken) -> bool:
        return token.leader and token.id == self._id

    def __call__(self) -> bool:  # is_leader interface for SchedulerService
        return True

    def is_holder(self) -> bool:
        """Side-effect-free leadership check (no acquisition attempt)."""
        return True

    def leader_address(self) -> str:
        """Advertised address of the current leader ("" = unknown/self)."""
        return ""


class FileLeaseLeader:
    """Lease file on shared storage: holder renews mtime; takeover after
    lease_duration of silence. Single-writer via atomic create/replace.

    Safety model: the lease file carries a monotonic **fencing counter**,
    incremented on every takeover. First acquisition uses O_EXCL so exactly
    one creator wins; takeover of an expired lease writes fence+1 and
    re-reads to confirm (if two candidates interleave, the later writer's
    file survives and the earlier one's re-read or validate() fails on the
    holder/fence mismatch). A validate-then-publish window remains — a
    candidate can take over after validate() returns and before the publish
    lands — which file storage cannot close without write-time fencing;
    that residual window is safe here because event application is
    idempotent and a deposed leader's events are re-derived identically by
    the new leader (scheduler.go:225-233 recovery semantics)."""

    def __init__(
        self,
        path: str,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        identity: str | None = None,
        advertise: str = "",
    ):
        self.path = path
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4()}"
        # gRPC address peers can reach this instance at, written into the
        # lease so followers can proxy leader-only RPCs (the reference's
        # leader connection from the Lease holder identity,
        # scheduler reports proxying).
        self.advertise = advertise
        self._epoch = 0
        self._fence = 0

    def _read(self):
        """Returns (holder, ts, fence, address); holder None only when the
        file does not exist. A torn/corrupt file (killed mid-write, disk
        full) parses as holder="" with an expired ts, so candidates recover
        it through the fenced takeover path — O_EXCL creation would
        otherwise fail forever against a file that exists but never
        parses."""
        try:
            with open(self.path) as f:
                raw = f.read()
        except FileNotFoundError:
            return None, 0.0, 0, ""
        try:
            parts = raw.strip().split("\n")
            holder, ts = parts[0], float(parts[1])
            fence = int(parts[2]) if len(parts) > 2 else 0
            address = parts[3] if len(parts) > 3 else ""
            if not holder:
                raise ValueError("empty holder")
            return holder, ts, fence, address
        except (ValueError, IndexError):
            return "", 0.0, 0, ""

    def _write(self, now: float, fence: int):
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{self.identity}\n{now}\n{fence}\n{self.advertise}")
        os.replace(tmp, self.path)

    def try_acquire_or_renew(self, now: float | None = None) -> bool:
        now = _time.time() if now is None else now
        holder, ts, fence, _ = self._read()
        if holder == self.identity:
            self._write(now, fence)
            self._fence = fence
            return True
        if holder is None:
            # First acquisition: O_EXCL so exactly one creator wins.
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return False
            with os.fdopen(fd, "w") as f:
                f.write(f"{self.identity}\n{now}\n1\n{self.advertise}")
            self._fence = 1
            self._epoch += 1
            return True
        if now - ts > self.lease_duration:
            self._write(now, fence + 1)
            # Re-read to confirm we won the race.
            holder2, _, fence2, _ = self._read()
            won = holder2 == self.identity and fence2 == fence + 1
            if won:
                self._fence = fence + 1
                self._epoch += 1
            return won
        return False

    def get_token(self) -> LeaderToken:
        leader = self.try_acquire_or_renew()
        return LeaderToken(leader=leader, id=f"{self.identity}:{self._epoch}")

    def validate(self, token: LeaderToken) -> bool:
        if not token.leader:
            return False
        holder, ts, fence, _ = self._read()
        return (
            holder == self.identity
            and fence == self._fence
            and token.id == f"{self.identity}:{self._epoch}"
            and _time.time() - ts <= self.lease_duration
        )

    def __call__(self) -> bool:
        return self.try_acquire_or_renew()

    def is_holder(self) -> bool:
        """True iff this instance currently holds a fresh lease — read-only
        (no acquisition attempt), safe to call on RPC paths."""
        holder, ts, _, _ = self._read()
        return holder == self.identity and _time.time() - ts <= self.lease_duration

    def leader_address(self) -> str:
        """The holder's advertised gRPC address ("" when the lease is
        stale, torn, or the holder advertised nothing)."""
        holder, ts, _, address = self._read()
        if holder and _time.time() - ts <= self.lease_duration:
            return address
        return ""
