"""Leader election: standalone and file-lease modes.

The reference supports `standalone` (always leader) and `kubernetes`
(coordination.k8s.io Lease) modes, with a LeaderToken whose validity gates
publishing (/root/reference/internal/leaderelection/leaderelection.go:16-63).
Kubernetes is out of scope here; the file-lease mode gives multi-process
HA on a shared filesystem with the same token semantics: a cycle captures a
token at its start, and publishes only validate against that token — losing
leadership mid-cycle invalidates the token so the next leader re-derives
events idempotently (scheduler.go:225-233).
"""

from __future__ import annotations

import os
import time as _time
import uuid
from dataclasses import dataclass


@dataclass(frozen=True)
class LeaderToken:
    leader: bool
    id: str = ""


class StandaloneLeader:
    """Always the leader (leader.mode=standalone)."""

    def __init__(self):
        self._id = str(uuid.uuid4())

    def get_token(self) -> LeaderToken:
        return LeaderToken(leader=True, id=self._id)

    def validate(self, token: LeaderToken) -> bool:
        return token.leader and token.id == self._id

    def __call__(self) -> bool:  # is_leader interface for SchedulerService
        return True


class FileLeaseLeader:
    """Lease file on shared storage: holder renews mtime; takeover after
    lease_duration of silence. Single-writer via atomic create/replace."""

    def __init__(
        self,
        path: str,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        identity: str | None = None,
    ):
        self.path = path
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4()}"
        self._epoch = 0

    def _read(self):
        try:
            with open(self.path) as f:
                holder, ts = f.read().strip().split("\n")
                return holder, float(ts)
        except (FileNotFoundError, ValueError):
            return None, 0.0

    def _write(self, now: float):
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{self.identity}\n{now}")
        os.replace(tmp, self.path)

    def try_acquire_or_renew(self, now: float | None = None) -> bool:
        now = _time.time() if now is None else now
        holder, ts = self._read()
        if holder == self.identity:
            self._write(now)
            return True
        if holder is None or now - ts > self.lease_duration:
            self._write(now)
            # Re-read to confirm we won the race.
            holder, _ = self._read()
            won = holder == self.identity
            if won:
                self._epoch += 1
            return won
        return False

    def get_token(self) -> LeaderToken:
        leader = self.try_acquire_or_renew()
        return LeaderToken(leader=leader, id=f"{self.identity}:{self._epoch}")

    def validate(self, token: LeaderToken) -> bool:
        if not token.leader:
            return False
        holder, ts = self._read()
        return (
            holder == self.identity
            and token.id == f"{self.identity}:{self._epoch}"
            and _time.time() - ts <= self.lease_duration
        )

    def __call__(self) -> bool:
        return self.try_acquire_or_renew()
