"""Executor-side node classification: pools, node types, node groups.

Port of /root/reference/internal/executor/node/node_group.go: each node's
POOL comes from a configurable node label (falling back to the cluster's
pool), with a "-reserved" suffix appended when the node carries a
reservation taint (reservedNodePoolSuffix, node_group.go:91-93); its TYPE
comes from a node-type label, else from the sorted id of the configured
tolerated taints it carries (filterToleratedTaints + nodeGroupId — taints
the executor tolerates are exactly what distinguishes node groups), else
"none". GroupNodesByType buckets nodes for per-type utilisation reports.

Node dicts are the agent's heartbeat records: {"id", "labels": {...},
"taints": [{"key","value","effect"}, ...], ...}.
"""

from __future__ import annotations

DEFAULT_NODE_TYPE = "none"
RESERVATION_TAINT_KEY = "armadaproject.io/reservation"


class NodeInfoService:
    def __init__(
        self,
        cluster_pool: str = "default",
        node_pool_label: str = "armadaproject.io/pool",
        node_type_label: str = "armadaproject.io/node-type",
        reserved_node_pool_suffix: str = "reserved",
        tolerated_taints: tuple = (),
    ):
        self.cluster_pool = cluster_pool
        self.node_pool_label = node_pool_label
        self.node_type_label = node_type_label
        self.reserved_node_pool_suffix = reserved_node_pool_suffix
        # The reservation taint is always tolerated (node_group.go:42-44).
        self.tolerated_taints = set(tolerated_taints) | {RESERVATION_TAINT_KEY}

    def get_pool(self, node: dict) -> str:
        pool = node.get("labels", {}).get(
            self.node_pool_label, self.cluster_pool
        )
        if self.reserved_node_pool_suffix and self._reservation(node):
            pool = f"{pool}-{self.reserved_node_pool_suffix}"
        return pool

    def _reservation(self, node: dict) -> str:
        for taint in node.get("taints", ()):
            if taint.get("key") == RESERVATION_TAINT_KEY and taint.get("value"):
                return taint["value"]
        return ""

    def get_type(self, node: dict) -> str:
        label = node.get("labels", {}).get(self.node_type_label)
        if label:
            return label
        relevant = sorted(
            t["key"]
            for t in node.get("taints", ())
            if t.get("key") in self.tolerated_taints
            and t.get("key") != RESERVATION_TAINT_KEY
        )
        return ",".join(relevant) if relevant else DEFAULT_NODE_TYPE

    def group_nodes_by_type(self, nodes: list[dict]) -> dict[str, list[dict]]:
        groups: dict[str, list[dict]] = {}
        for node in nodes:
            groups.setdefault(self.get_type(node), []).append(node)
        return groups

    def decorate(self, nodes: list[dict]) -> list[dict]:
        """Heartbeat enrichment: every node dict gains its derived pool and
        node type, so the scheduler sees per-node pools (a cluster can
        span pools, scheduling_algo.go union semantics) and reports can
        group by type."""
        out = []
        for node in nodes:
            node = dict(node)
            node.setdefault("pool", self.get_pool(node))
            node["node_type"] = self.get_type(node)
            out.append(node)
        return out
