"""Lookout ingester: an INDEPENDENT materialized view of the event log.

The reference runs three ingesters off the same Pulsar stream, one per
view (/root/reference/internal/lookoutingester/{ingester,instructions,
lookoutdb}.go): lookout's view is denormalized job/run rows for the UI,
materialized separately from the scheduler's jobdb so UI load never
contends with scheduling and the view can lag/catch up independently.
This ingester does the same against the shared log: its own cursor, its
own row store, and lag observability (common/ingest topic_delay_monitor).
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field

from .. import events as ev


@dataclass
class LookoutRun:
    run_id: str
    executor: str = ""
    node: str = ""
    pool: str = ""
    leased: float = 0.0
    started: float = 0.0
    finished: float = 0.0
    state: str = "leased"
    error: str = ""
    # Executor diagnostic dump (job_run.debug, getjobrundebugmessage.go).
    debug: str = ""
    # Why the scheduler ended this run (preemption reason — the
    # getjobrunschedulerterminationreason.go surface).
    termination_reason: str = ""


@dataclass
class LookoutRow:
    """Denormalized job row (lookoutdb insertion.go job/job_run tables)."""

    job_id: str
    queue: str
    jobset: str
    state: str = "queued"
    priority: int = 0
    priority_class: str = ""
    requests: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    submitted: float = 0.0
    last_transition: float = 0.0
    cancelled: float = 0.0
    error: str = ""
    error_category: str = ""
    runs: list = field(default_factory=list)

    @property
    def latest_run(self) -> LookoutRun | None:
        return self.runs[-1] if self.runs else None


class LookoutStore:
    """The lookout view: rows by job id + jobset/queue indexes, built by
    replaying the log. Thread-safe (UI reads while the ingester writes)."""

    def __init__(self, log, error_rules=(), checkpoint=None):
        self.log = log
        self.error_rules = error_rules
        self.rows: dict[str, LookoutRow] = {}
        self.run_to_job: dict[str, str] = {}  # run_id -> job_id
        self.cursor = 0
        self._lock = threading.Lock()
        if checkpoint is not None:
            # Bounded restart (services/checkpoint.py): seed rows, then
            # sync() replays only the suffix past the cursor.
            self.cursor, state = checkpoint
            self.rows.update(state["rows"])
            self.run_to_job.update(state["run_to_job"])
        self.cursor = max(self.cursor, log.start_offset)

    def checkpoint_state(self):
        with self._lock:
            # Rows are mutated in place by _apply: deep-copy so a
            # checkpoint written after more syncs doesn't see newer state
            # under an older cursor.
            return self.cursor, {
                "rows": copy.deepcopy(self.rows),
                "run_to_job": dict(self.run_to_job),
            }

    # ---- ingestion ----

    def sync(self, limit: int = 10_000) -> int:
        """Apply new log entries to the view; returns number applied."""
        applied = 0
        while True:
            entries = self.log.read(self.cursor, limit)
            if not entries:
                return applied
            with self._lock:
                for entry in entries:
                    for event in entry.sequence.events:
                        self._apply(entry.sequence, event)
                self.cursor = entries[-1].offset + 1
            applied += len(entries)

    @property
    def lag_events(self) -> int:
        """Events behind the log end (ingester lag metric)."""
        return max(0, self.log.end_offset - self.cursor)

    def _apply(self, seq, event):
        from ..jobdb.ingest import categorize_error

        if isinstance(event, ev.SubmitJob):
            if event.job.id in self.rows:
                return
            self.rows[event.job.id] = LookoutRow(
                job_id=event.job.id,
                queue=seq.queue,
                jobset=seq.jobset,
                priority=event.job.priority,
                priority_class=event.job.priority_class,
                requests=dict(event.job.requests),
                annotations=dict(event.job.annotations),
                submitted=event.created,
                last_transition=event.created,
            )
            return
        if isinstance(event, ev.CancelJobSet):
            for row in self.rows.values():
                if (
                    row.queue == seq.queue
                    and row.jobset == seq.jobset
                    and row.state
                    in ("queued", "leased", "pending", "running")
                ):
                    row.state = "cancelled"
                    row.cancelled = event.created
                    row.last_transition = event.created
            return
        row = self.rows.get(getattr(event, "job_id", ""))
        if row is None:
            return
        t = getattr(event, "created", 0.0)
        if isinstance(event, ev.CancelJob):
            row.state, row.cancelled, row.last_transition = "cancelled", t, t
        elif isinstance(event, ev.ReprioritiseJob):
            row.priority = event.priority
        elif isinstance(event, ev.JobRunLeased):
            row.state, row.last_transition = "leased", t
            row.runs.append(
                LookoutRun(
                    run_id=event.run_id,
                    executor=event.executor,
                    node=event.node_id,
                    pool=event.pool,
                    leased=t,
                )
            )
            self.run_to_job[event.run_id] = row.job_id
        elif isinstance(event, ev.JobRunPending):
            row.state, row.last_transition = "pending", t
            if row.latest_run:
                row.latest_run.state = "pending"
        elif isinstance(event, ev.JobRunRunning):
            row.state, row.last_transition = "running", t
            if row.latest_run:
                row.latest_run.state = "running"
                row.latest_run.started = t
        elif isinstance(event, ev.JobRunSucceeded):
            if row.latest_run:
                row.latest_run.state = "succeeded"
                row.latest_run.finished = t
        elif isinstance(event, ev.JobSucceeded):
            row.state, row.last_transition = "succeeded", t
        elif isinstance(event, ev.JobRunPreempted):
            row.state, row.last_transition = "preempted", t
            if row.latest_run:
                row.latest_run.state = "preempted"
                row.latest_run.finished = t
                row.latest_run.termination_reason = event.reason
        elif isinstance(event, ev.JobRunErrors):
            if row.latest_run:
                row.latest_run.state = "failed"
                row.latest_run.finished = t
                row.latest_run.error = event.error
                row.latest_run.debug = event.debug
            row.error = event.error
            row.error_category = categorize_error(event.error, self.error_rules)
        elif isinstance(event, ev.JobRequeued):
            row.state, row.last_transition = "queued", t
        elif isinstance(event, ev.JobErrors):
            row.state, row.last_transition = "failed", t
            row.error = event.error
            row.error_category = categorize_error(event.error, self.error_rules)

    # ---- reads (thread-safe snapshots) ----

    def all_rows(self) -> list[LookoutRow]:
        with self._lock:
            return list(self.rows.values())

    def get(self, job_id: str) -> LookoutRow | None:
        with self._lock:
            return self.rows.get(job_id)

    def materialize(self, rows, convert):
        """convert(row) for each row under the store lock: rows mutate in
        place under the ingester, so converters get internally consistent
        snapshots (queryapi page materialization)."""
        with self._lock:
            return [convert(r) for r in rows]

    def get_run(self, run_id: str) -> LookoutRun | None:
        """Run-level drilldown (job_run row by run_id)."""
        with self._lock:
            row = self.rows.get(self.run_to_job.get(run_id, ""))
            if row is None:
                return None
            for r in row.runs:
                if r.run_id == run_id:
                    return r
            return None

    def prune(self, older_than: float) -> int:
        """Drop terminal rows older than the retention window (the lookout
        pruner, internal/lookout/pruner)."""
        terminal = ("succeeded", "failed", "cancelled", "preempted")
        with self._lock:
            drop = [
                jid
                for jid, row in self.rows.items()
                if row.state in terminal and row.last_transition < older_than
            ]
            for jid in drop:
                for run in self.rows[jid].runs:
                    self.run_to_job.pop(run.run_id, None)
                del self.rows[jid]
        return len(drop)
