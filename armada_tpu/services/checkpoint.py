"""View checkpoints + log compaction: bounded restart.

The reference restarts from materialized views — scheduler state lives in
Postgres tables with monotone serials (database/migrations/
001_initialize_schema.up.sql:1-91) that the scheduler delta-polls
(scheduler.go:441 syncState), lookout rows are pruned on retention
(internal/lookout/pruner/pruner.go), and Pulsar retention drops
acknowledged history. Without these, a log-is-the-checkpoint design pays
O(history) on every restart and the log grows forever.

Here the same bound comes from periodic view checkpoints: each registered
view serializes (cursor, state) atomically to disk; a restarted process
loads the checkpoint and replays only the log suffix past its cursor
(recover = checkpoint + delta). Once every view has a checkpoint at or
past an offset, the log segments below it are fully materialized
everywhere and can be deleted (FileEventLog.compact), which also bounds
disk and the in-memory log index.

Checkpoint files are pickles (same trust domain as the log on local disk),
crc-guarded and written via tmp+fsync+rename so a crash mid-write leaves
the previous good checkpoint in place.
"""

from __future__ import annotations

import os
import pickle
import zlib

FORMAT_VERSION = 1


class CheckpointStore:
    """One atomic (cursor, state) file per view name."""

    def __init__(self, directory: str, crash_hook=None):
        self.dir = directory
        # Crash-point seam (tests/test_checkpoint.py fuzz): called with a
        # site label at each durability boundary; a hook that raises
        # simulates a process crash at exactly that point.
        self.crash_hook = crash_hook
        os.makedirs(directory, exist_ok=True)
        # A crash between tmp-write and rename strands a stale ".tmp":
        # never loaded (load reads only the renamed file) but never
        # cleaned up either. Sweep on open — any writer of these files
        # is dead by the time a store is constructed over the directory.
        for fn in os.listdir(directory):
            if fn.endswith(".ckpt.tmp"):
                try:
                    os.remove(os.path.join(directory, fn))
                except OSError:
                    pass

    def _crash_point(self, site: str) -> None:
        if self.crash_hook is not None:
            self.crash_hook(site)

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, f"{name}.ckpt")

    def save(self, name: str, cursor: int, state) -> None:
        payload = pickle.dumps(
            (FORMAT_VERSION, cursor, state), protocol=pickle.HIGHEST_PROTOCOL
        )
        tmp = self._path(name) + ".tmp"
        self._crash_point(f"save:{name}:before-tmp")
        with open(tmp, "wb") as f:
            f.write(zlib.crc32(payload).to_bytes(4, "big") + payload)
            f.flush()
            os.fsync(f.fileno())
        self._crash_point(f"save:{name}:after-tmp")
        os.replace(tmp, self._path(name))
        self._crash_point(f"save:{name}:after-rename")

    def load(self, name: str):
        """Returns (cursor, state) or None (absent/corrupt — corrupt means
        the tmp+rename contract was bypassed; the caller falls back to
        whatever log replay is still possible)."""
        try:
            with open(self._path(name), "rb") as f:
                rec = f.read()
        except FileNotFoundError:
            return None
        if len(rec) < 4:
            return None
        payload = rec[4:]
        if zlib.crc32(payload) != int.from_bytes(rec[:4], "big"):
            return None
        try:
            version, cursor, state = pickle.loads(payload)
        except Exception:
            return None
        if version != FORMAT_VERSION:
            return None
        return cursor, state


class CheckpointManager:
    """Checkpoints registered views and compacts the log behind them.

    Views implement `checkpoint_state() -> (cursor, state)`. Compaction
    uses the min cursor across the views saved in THIS pass, so a segment
    is only deleted once every registered view has durably materialized
    it. Callers must register every log consumer that replays on restart —
    an unregistered consumer would lose its history to compaction.
    """

    def __init__(self, store: CheckpointStore, log):
        self.store = store
        self.log = log
        self._views: dict[str, object] = {}

    def register(self, name: str, view) -> None:
        self._views[name] = view

    def save_all(self) -> int:
        """Checkpoint every view; returns the min checkpointed cursor."""
        cursors = []
        for name, view in self._views.items():
            cursor, state = view.checkpoint_state()
            self.store.save(name, cursor, state)
            cursors.append(cursor)
        return min(cursors) if cursors else 0

    def checkpoint_and_compact(self) -> int:
        """One maintenance pass: save all views, drop fully-covered log
        segments. Returns the number of segments removed."""
        cursor = self.save_all()
        self.store._crash_point("compact:before")
        return self.log.compact(cursor)
