"""Jobset-keyed sharded ingest: the front door's write path.

The reference survives submit floods by partitioning its event topic by
jobset key (Pulsar partitioned topics, jobset-keyed routing in
internal/common/pulsarutils/jobsetevents/) and running one ingester per
partition. Same shape here: a submission is acknowledged once it is
DURABLE in its jobset's shard WAL (a crash-recovering FileEventLog —
torn tails truncate, the append retries, the client's ack means the
bytes survived); per-shard ingesters then deliver WAL entries into the
main event log, where every existing view (scheduler jobdb, lookout,
event index, watch streams) consumes them unchanged.

Delivery is ordered and exactly-once across crash/restart:

  ordered       a jobset maps to exactly one shard (stable crc32 key),
                and a shard delivers its WAL in offset order — so every
                jobset sees its events in submission order.

  exactly-once  each delivered EventSequence is stamped with an
                idempotent-producer marker "fd<shard>:<wal offset>".
                The durable drain state (cursor + the main-log offset at
                the last save, tmp+fsync+rename) only advances AFTER the
                publish, so a crash between publish and save redelivers;
                recovery scans the main log's suffix from the saved
                offset for its own markers and skips what already
                landed. Lost-ack is impossible (the WAL is durable
                before the ack; the cursor never passes an undelivered
                entry); double-apply is impossible (the marker scan
                suppresses redelivery, and the jobdb's idempotent
                SubmitJob guard backstops it).

Chaos integration (services/chaos.py, existing FaultPlan kinds):

  torn_log_write  target "shard-<i>" (or "*") tears the shard WAL
                  append mid-record — recovery truncates, the append
                  retries, the ack is only ever sent for durable bytes.
  network_partition  target "shard-<i>" severs the shard ingester from
                  the store for the window: the WAL keeps acking, lag
                  grows, delivery resumes on heal (acked work is
                  delayed, never lost).
  executor_crash  target "shard-<i>" kills the shard ingester mid-batch
                  (ShardCrashed); FrontDoor.pump restarts it from its
                  durable state — the crash/restart path the
                  exactly-once machinery exists for.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
import zlib
from dataclasses import replace

from ..events import InMemoryEventLog
from ..events.model import EventSequence
from .admission import DeadlineExpired

# Marker prefix: "fd<shard>:<wal offset>".
_MARKER = "fd{shard}:{offset}"


def shard_of(queue: str, jobset: str, num_shards: int) -> int:
    """Stable jobset-keyed routing (crc32, not hash(): Python's string
    hash is salted per process — two processes must agree)."""
    return zlib.crc32(f"{queue}/{jobset}".encode()) % max(1, num_shards)


class ShardCrashed(RuntimeError):
    """Injected shard-ingester crash (chaos `executor_crash` on target
    "shard-<i>"): the delivery batch aborts wherever it was — published
    entries are in the main log, the cursor is NOT saved — and the owner
    restarts the shard from durable state."""

    def __init__(self, index: int):
        super().__init__(f"shard-{index} ingester crashed mid-batch")
        self.index = index


class IngestShard:
    """One shard: a durable WAL (the ack point) + a cursor-tracked
    ingester delivering into the main log with exactly-once markers."""

    def __init__(
        self,
        index: int,
        main_log,
        directory: str | None = None,
        fault_plan=None,
        clock=None,
        crash_hook=None,
        wal=None,
    ):
        self.index = index
        self.main_log = main_log
        self.directory = directory
        self.fault_plan = fault_plan
        self.clock = clock if clock is not None else _time.time
        # Test seam: called once per WAL entry before delivery; lets the
        # soak's --inject-loss deliberately drop an acked entry (the gate
        # must catch exactly this) and tests kill delivery mid-batch.
        self.crash_hook = crash_hook
        self.delivered_total = 0
        self.duplicates_suppressed = 0
        self.restarts = 0
        if wal is not None:
            # In-memory restart path: the WAL object survives (only the
            # ingester state is "lost"); recovery rebuilds the cursor
            # from the marker scan alone.
            self.wal = wal
        elif directory is not None:
            from ..services.chaos import CrashRecoveringLog

            os.makedirs(directory, exist_ok=True)
            self.wal = CrashRecoveringLog(
                directory, fault_plan, clock=self.clock,
                target=f"shard-{index}",
            )
        else:
            self.wal = InMemoryEventLog()
        self.cursor = 0
        self._saved_main_offset = 0
        self._delivered: set[int] = set()  # redelivery-window dedup
        self._recover()

    # ---- durable drain state ----

    def _state_path(self) -> str:
        return os.path.join(self.directory, "drain.json")

    def _save_state(self) -> None:
        if self.directory is None:
            self._saved_main_offset = self.main_log.end_offset
            return
        state = {
            "cursor": self.cursor,
            "main_offset": self.main_log.end_offset,
        }
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path())
        self._saved_main_offset = state["main_offset"]

    def _recover(self) -> None:
        """Load the durable cursor, then scan the main log's suffix for
        this shard's markers at or past it — entries published by a
        previous incarnation whose cursor save never landed. Those are
        skipped on redelivery: exactly-once across the crash."""
        if self.directory is not None:
            try:
                with open(self._state_path()) as f:
                    state = json.load(f)
                self.cursor = int(state.get("cursor", 0))
                self._saved_main_offset = int(state.get("main_offset", 0))
            except (FileNotFoundError, json.JSONDecodeError, ValueError):
                self.cursor = 0
                self._saved_main_offset = 0
        prefix = f"fd{self.index}:"
        cur = max(self._saved_main_offset, self.main_log.start_offset)
        self._delivered = set()
        while True:
            try:
                entries = self.main_log.read(cur, 5000)
            except Exception as e:  # CompactedLogError: a concurrent
                # compact() advanced start_offset past our saved cursor —
                # skip the compacted prefix (its entries are materialized
                # in every checkpointed view, below any live dedup window)
                # and keep scanning the surviving suffix.
                if type(e).__name__ != "CompactedLogError":
                    raise
                cur = self.main_log.start_offset
                continue
            if not entries:
                break
            for entry in entries:
                marker = getattr(entry.sequence, "ingest_marker", "")
                if marker.startswith(prefix):
                    off = int(marker[len(prefix):])
                    if off >= self.cursor:
                        self._delivered.add(off)
            cur = entries[-1].offset + 1

    # ---- the ack point ----

    def append(self, sequence: EventSequence) -> int:
        """Durable WAL append; returning IS the acknowledgement. Torn
        writes (chaos) recover-and-retry inside the crash-recovering
        WAL, so an ack always means the bytes are on disk."""
        return self.wal.publish(sequence)

    @property
    def lag(self) -> int:
        """Acked-but-undelivered entries (the ingest lag SLO input)."""
        return max(0, self.wal.end_offset - self.cursor)

    # ---- delivery ----

    def partitioned(self, now: float | None = None) -> bool:
        if self.fault_plan is None:
            return False
        now = self.clock() if now is None else now
        return (
            self.fault_plan.active(
                "network_partition", f"shard-{self.index}", now
            )
            is not None
        )

    def deliver(self, limit: int = 10_000, now: float | None = None) -> int:
        """Deliver up to `limit` WAL entries into the main log, in
        order. Returns entries processed (delivered + suppressed).
        Raises ShardCrashed mid-batch under an injected crash — durable
        state is then exactly as a killed process would leave it."""
        now = self.clock() if now is None else now
        if self.partitioned(now):
            return 0
        entries = self.wal.read(self.cursor, limit)
        if not entries:
            return 0
        processed = 0
        # NO state save on the crash path: a killed process never gets
        # to persist its cursor, so everything published in this batch
        # sits PAST the durable cursor — exactly the redelivery window
        # the restarted ingester's marker scan must dedup.
        for entry in entries:
            if (
                processed  # crash MID-batch: at least one entry is
                # already published past the durable cursor, so the
                # restart must dedup it — the exactly-once window
                and self.fault_plan is not None
                and self.fault_plan.fire(
                    "executor_crash", f"shard-{self.index}", now
                )
            ):
                raise ShardCrashed(self.index)
            dropped = False
            if self.crash_hook is not None:
                dropped = bool(self.crash_hook(self, entry))
            if entry.offset in self._delivered:
                self.duplicates_suppressed += 1
            elif not dropped:
                self.main_log.publish(
                    replace(
                        entry.sequence,
                        ingest_marker=_MARKER.format(
                            shard=self.index, offset=entry.offset
                        ),
                    )
                )
                self.delivered_total += 1
            self.cursor = entry.offset + 1
            processed += 1
        self._save_state()
        self._delivered = {o for o in self._delivered if o >= self.cursor}
        return processed


class FrontDoor:
    """N ingest shards + (optional) admission control, one object the
    transport and SubmitService share.

    `append` is the post-validation enqueue: it checks the propagated
    deadline (drop early — an expired submission must never be acked)
    then routes to the jobset's shard WAL. `pump` runs every shard's
    ingester; an injected shard crash is met with an in-place restart
    from durable state, the same recovery a supervised process performs.
    """

    def __init__(
        self,
        main_log,
        num_shards: int = 4,
        directory: str | None = None,
        admission=None,
        fault_plan=None,
        clock=None,
        metrics=None,
    ):
        self.main_log = main_log
        self.num_shards = max(1, int(num_shards))
        self.directory = directory
        self.admission = admission
        self.fault_plan = fault_plan
        self.clock = clock if clock is not None else _time.time
        self.metrics = metrics
        self.deadline_drops = {"gate": 0, "enqueue": 0}
        self._lock = threading.Lock()
        self.shards = [
            self._make_shard(i) for i in range(self.num_shards)
        ]

    def _make_shard(self, i: int, wal=None) -> IngestShard:
        return IngestShard(
            i,
            self.main_log,
            directory=(
                os.path.join(self.directory, f"shard-{i:02d}")
                if self.directory is not None
                else None
            ),
            fault_plan=self.fault_plan,
            clock=self.clock,
            wal=wal,
        )

    # ---- admission + deadline + enqueue (the submit path) ----

    def admit(self, tenant: str, n: int = 1, now: float | None = None) -> None:
        if self.admission is not None:
            self.admission.admit(tenant, n, now=now)

    def note_deadline_drop(self, stage: str) -> None:
        with self._lock:
            self.deadline_drops[stage] = self.deadline_drops.get(stage, 0) + 1
        m = self.metrics
        if m is not None and getattr(m, "registry", None) is not None:
            m.frontdoor_deadline_drops.labels(stage=stage).inc()

    def append(
        self,
        sequence: EventSequence,
        deadline_ts: float | None = None,
        now: float | None = None,
    ) -> int:
        """Durable enqueue (the ack). The deadline check sits immediately
        before the WAL append: expired work is dropped here, whole —
        after this point the submission is acked and ALWAYS applies."""
        now = self.clock() if now is None else now
        if deadline_ts is not None and now >= deadline_ts:
            self.note_deadline_drop("enqueue")
            raise DeadlineExpired(
                "enqueue",
                f"{now - deadline_ts:.3f}s past deadline at the shard WAL",
            )
        i = shard_of(sequence.queue, sequence.jobset, self.num_shards)
        return self.shards[i].append(sequence)

    # ---- the ingest loop ----

    def pump(self, limit: int = 10_000, now: float | None = None) -> int:
        """One delivery pass over every shard. Injected shard crashes
        restart the shard from its durable state (counted), exactly as a
        supervisor would; the pass then continues with the next shard —
        one crashing shard never wedges the others."""
        total = 0
        for i, shard in enumerate(self.shards):
            try:
                total += shard.deliver(limit, now=now)
            except ShardCrashed:
                # Restart from durable state only (the file-backed WAL
                # recovers itself; an in-memory WAL object survives the
                # "process" by construction). Counters carry over — they
                # describe the shard, not the incarnation.
                old_wal = (
                    shard.wal if self.directory is None else None
                )
                counters = (
                    shard.restarts + 1,
                    shard.delivered_total,
                    shard.duplicates_suppressed,
                )
                self.shards[i] = self._make_shard(i, wal=old_wal)
                (
                    self.shards[i].restarts,
                    self.shards[i].delivered_total,
                    self.shards[i].duplicates_suppressed,
                ) = counters
                # The metrics watermark too, or _observe_metrics would
                # re-count the whole pre-crash delivery history as a
                # fresh counter delta after every restart.
                self.shards[i]._metric_last = getattr(
                    shard, "_metric_last", (0, 0)
                )
        self._observe_metrics()
        return total

    def drain(self, now: float | None = None, max_passes: int = 1000) -> None:
        """Pump until every shard's lag is zero (or a partition window
        holds it open — callers on a virtual clock advance time and call
        again)."""
        for _ in range(max_passes):
            self.pump(now=now)
            if self.max_lag() == 0 or any(
                s.partitioned(now) for s in self.shards
            ):
                return

    def max_lag(self) -> int:
        return max((s.lag for s in self.shards), default=0)

    def _observe_metrics(self) -> None:
        m = self.metrics
        if m is None or getattr(m, "registry", None) is None:
            return
        for shard in self.shards:
            label = str(shard.index)
            m.frontdoor_shard_lag.labels(shard=label).set(shard.lag)
            # Counters need deltas; track last-observed per shard.
            last = getattr(shard, "_metric_last", (0, 0))
            d_pub = shard.delivered_total - last[0]
            d_dup = shard.duplicates_suppressed - last[1]
            if d_pub > 0:
                m.frontdoor_delivered.labels(
                    shard=label, outcome="published"
                ).inc(d_pub)
            if d_dup > 0:
                m.frontdoor_delivered.labels(
                    shard=label, outcome="duplicate"
                ).inc(d_dup)
            shard._metric_last = (
                shard.delivered_total,
                shard.duplicates_suppressed,
            )

    # ---- introspection / lifecycle ----

    def checkpoint_state(self):
        """CheckpointManager view contract: (cursor, state). The cursor
        is the lowest main-log offset any shard's recovery marker scan
        could need — compaction must never delete the redelivery-dedup
        window out from under a restarting shard. A fully drained shard
        (lag 0: cursor saved past every WAL entry, nothing left to
        redeliver) needs no window at all and reports the log's end, so
        idle shards never pin compaction at offset 0 forever."""
        cursors = [
            s._saved_main_offset if s.lag > 0 else self.main_log.end_offset
            for s in self.shards
        ]
        return (min(cursors) if cursors else 0, {})

    def snapshot(self) -> dict:
        doc = {
            "shards": [
                {
                    "shard": s.index,
                    "lag": s.lag,
                    "delivered": s.delivered_total,
                    "duplicates_suppressed": s.duplicates_suppressed,
                    "restarts": s.restarts,
                    "partitioned": s.partitioned(),
                }
                for s in self.shards
            ],
            "deadline_drops": dict(self.deadline_drops),
        }
        if self.admission is not None:
            doc.update(self.admission.snapshot())
        return doc

    def close(self) -> None:
        for shard in self.shards:
            close = getattr(shard.wal, "close", None)
            if close is not None:
                close()
