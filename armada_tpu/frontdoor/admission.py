"""Per-tenant admission control: token buckets + quota-weighted shedding.

The reference protects its submit path with per-queue rate limits
(internal/server/submit rate limiting, config.yaml:105-108 analogues) and
sheds work when the store backs up; the fair-allocation literature in
PAPERS.md (1803.00922 on Mesos, 1404.2266 proportional fairness) argues
that overload shedding must be tenant-aware — a global gate lets one hot
queue starve every other tenant's intake.

Two regimes, one `admit()` surface:

  normal    each tenant draws from its own token bucket (rate/burst) and
            a shared global bucket. A tenant flooding past its rate is
            shed with a computed retry-after while every other tenant's
            bucket is untouched.

  overload  the downstream gate (services/backpressure.CompositeGate —
            store capacity, ingest lag, round-deadline pressure) is
            unhealthy. Intake drops to a trickle (`overload_rate`)
            apportioned by QUOTA WEIGHT (1/priorityFactor, the same
            weight fair share uses): each tenant's trickle bucket refills
            at overload_rate * w / sum(w over recently active tenants),
            so a hot tenant exhausts its slice and is shed first while
            light high-quota tenants keep a (reduced) flow. The shed
            reason carries the downstream gate's own reason.

Every rejection is an `AdmissionError` with `retry_after_s` — the
transport maps it to RESOURCE_EXHAUSTED plus a `retry-after` trailing
header so clients back off deliberately instead of timing out
(ApiClient/ProtoApiClient honor it with a bounded jittered backoff).

`DeadlineExpired` is the submit wire's deadline propagation: the client
deadline travels to the server gate and the ingest enqueue; work that
cannot possibly be acknowledged in time is dropped EARLY (before the
durable WAL append — after the append it is acked and always applies,
never half-applied).
"""

from __future__ import annotations

import threading
import time as _time


class AdmissionError(RuntimeError):
    """Submission shed by admission control. `retry_after_s` is the
    server-computed earliest useful retry instant (seconds from now)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(
            f"{reason}; retry after {max(0.0, retry_after_s):.3f}s"
        )
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))


class DeadlineExpired(RuntimeError):
    """The caller's deadline expired before the work could be durably
    acknowledged; dropped at `stage` ("gate" = before any processing,
    "enqueue" = before the WAL append). Never raised after the ack."""

    def __init__(self, stage: str, detail: str = ""):
        super().__init__(
            f"deadline expired before {stage}"
            + (f": {detail}" if detail else "")
        )
        self.stage = stage


class TokenBucket:
    """Classic token bucket. `try_take(n)` returns 0.0 on admit or the
    seconds until n tokens will be available (the retry-after hint).
    Rates are tokens/second; `now` is injectable (virtual clocks)."""

    def __init__(self, rate: float, burst: float, now: float | None = None):
        self.rate = max(1e-9, float(rate))
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._last = now

    def _refill(self, now: float) -> None:
        if self._last is None:
            self._last = now
        elapsed = max(0.0, now - self._last)
        self._last = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)

    def try_take(self, n: float = 1.0, now: float | None = None) -> float:
        now = _time.monotonic() if now is None else now
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        deficit = n - self.tokens
        return deficit / self.rate


class TenantAdmission:
    """Tenant-aware admission in front of the backpressure stack.

    `quota_of(tenant) -> weight` supplies the fair-share weight
    (1/priorityFactor; ControlPlane wires it to the queue registry) —
    raising a hot tenant's priority factor shrinks its overload slice,
    the runbook's "adjust quota" lever. `downstream` is any object with
    check() -> (healthy, reason) (CompositeGate / StoreHealthMonitor).
    """

    def __init__(
        self,
        tenant_rate: float = 1000.0,
        tenant_burst: float = 2000.0,
        global_rate: float = 10_000.0,
        global_burst: float = 20_000.0,
        overload_rate: float = 100.0,
        downstream=None,
        quota_of=None,
        metrics=None,
        active_window_s: float = 30.0,
    ):
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = float(tenant_burst)
        self.overload_rate = float(overload_rate)
        self.downstream = downstream
        self.quota_of = quota_of
        self.metrics = metrics
        self.active_window_s = active_window_s
        self._global = TokenBucket(global_rate, global_burst)
        self._tenant: dict[str, TokenBucket] = {}
        self._trickle: dict[str, TokenBucket] = {}
        self._last_seen: dict[str, float] = {}  # overload-slice membership
        # admit() is called from concurrent gRPC worker threads: the
        # lock guards every bucket read-modify-write (a lost token
        # decrement would admit a flood past its configured rate) as
        # well as the counters feeding metrics and the lookout view.
        # Reentrant because _note runs inside the admit critical
        # section.
        self._lock = threading.RLock()
        self.admitted: dict[str, int] = {}
        self.shed: dict[str, int] = {}
        self.last_shed_reason: dict[str, str] = {}

    # ---- introspection (lookout /api/frontdoor) ----

    def snapshot(self) -> dict:
        with self._lock:
            tenants = sorted(
                set(self.admitted) | set(self.shed),
                key=lambda t: -(self.shed.get(t, 0)),
            )
            return {
                "tenants": [
                    {
                        "tenant": t,
                        "admitted": self.admitted.get(t, 0),
                        "shed": self.shed.get(t, 0),
                        "last_shed_reason": self.last_shed_reason.get(t, ""),
                    }
                    for t in tenants
                ],
            }

    # ---- the gate ----

    def _weight(self, tenant: str) -> float:
        if self.quota_of is None:
            return 1.0
        try:
            w = float(self.quota_of(tenant))
        except Exception:
            return 1.0
        return w if w > 0.0 else 1.0

    def _note(self, tenant: str, n: int, shed_reason: str | None) -> None:
        with self._lock:
            if shed_reason is None:
                self.admitted[tenant] = self.admitted.get(tenant, 0) + n
            else:
                self.shed[tenant] = self.shed.get(tenant, 0) + n
                self.last_shed_reason[tenant] = shed_reason
        m = self.metrics
        if m is not None and getattr(m, "registry", None) is not None:
            if shed_reason is None:
                m.frontdoor_admitted.labels(tenant=tenant).inc(n)
            else:
                # Reason label keeps cardinality bounded: the reason CLASS,
                # not the free-text downstream detail.
                kind = shed_reason.split(":", 1)[0]
                m.frontdoor_shed.labels(tenant=tenant, reason=kind).inc(n)

    def admit(self, tenant: str, n: int = 1, now: float | None = None) -> None:
        """Admit n submissions for `tenant` or raise AdmissionError.
        Pass `now` on a virtual clock (sim/soak); wall monotonic
        otherwise. Counting is per JOB, not per RPC, so one huge batch
        cannot sail under a per-request limit."""
        now = _time.monotonic() if now is None else now
        healthy, reason = (True, "")
        if self.downstream is not None:
            healthy, reason = self.downstream.check()
        with self._lock:
            if not healthy:
                self._last_seen[tenant] = now
                wait = self._trickle_take(tenant, n, now)
                if wait > 0.0:
                    shed_reason = f"overload:{reason}"
                    self._note(tenant, n, shed_reason)
                    raise AdmissionError(
                        f"control plane overloaded ({reason}); tenant "
                        f"{tenant!r} is over its quota-weighted overload "
                        "slice",
                        wait,
                    )
                self._note(tenant, n, None)
                return
            bucket = self._tenant.get(tenant)
            if bucket is None:
                bucket = self._tenant[tenant] = TokenBucket(
                    self.tenant_rate, self.tenant_burst, now=now
                )
            wait = bucket.try_take(n, now)
            if wait > 0.0:
                self._note(tenant, n, "tenantRate")
                raise AdmissionError(
                    f"tenant {tenant!r} exceeded its submission rate "
                    f"({self.tenant_rate:.0f}/s, burst "
                    f"{self.tenant_burst:.0f})",
                    wait,
                )
            wait = self._global.try_take(n, now)
            if wait > 0.0:
                # The tenant bucket already debited; refund so a globally
                # shed request does not double-charge the tenant's own
                # budget.
                bucket.tokens = min(bucket.burst, bucket.tokens + n)
                self._note(tenant, n, "globalRate")
                raise AdmissionError(
                    "front door exceeded the global submission rate "
                    f"({self._global.rate:.0f}/s)",
                    wait,
                )
            self._note(tenant, n, None)

    def _trickle_take(self, tenant: str, n: int, now: float) -> float:
        """Overload mode: one trickle bucket per recently active tenant,
        refilling at overload_rate x (its quota share). Rates are
        recomputed as the active set shifts, so a tenant going quiet
        returns its slice to the others."""
        stale = [
            t
            for t, ts in self._last_seen.items()
            if now - ts > self.active_window_s
        ]
        for t in stale:
            self._last_seen.pop(t, None)
            self._trickle.pop(t, None)
        total_w = sum(self._weight(t) for t in self._last_seen) or 1.0
        share = self._weight(tenant) / total_w
        rate = max(1e-9, self.overload_rate * share)
        bucket = self._trickle.get(tenant)
        if bucket is None:
            # A fresh overload bucket starts with one slice-second of
            # burst, not a full normal-mode burst: overload means drain,
            # not another burst window.
            bucket = self._trickle[tenant] = TokenBucket(
                rate, max(1.0, rate), now=now
            )
        else:
            bucket.rate = rate
            bucket.burst = max(1.0, rate)
        return bucket.try_take(n, now)
