"""Overload-hardened front door: sharded ingest + tenant admission.

The submit path at scale (ROADMAP item 5): jobset-keyed N-way sharded
ingest WALs with ordered, exactly-once delivery into the main event log
(`partition.py` — the Pulsar-partitioning analogue), per-tenant
token-bucket admission with quota-weighted overload shedding in front of
the backpressure stack (`admission.py`), and submit-wire deadline
propagation (expired work drops early, acked work always applies).
`tools/frontdoor_soak.py` is the chaos-soaked SLO gate over the whole
path.
"""

from .admission import (
    AdmissionError,
    DeadlineExpired,
    TenantAdmission,
    TokenBucket,
)
from .partition import FrontDoor, IngestShard, ShardCrashed, shard_of

__all__ = [
    "AdmissionError",
    "DeadlineExpired",
    "FrontDoor",
    "IngestShard",
    "ShardCrashed",
    "TenantAdmission",
    "TokenBucket",
    "shard_of",
]
