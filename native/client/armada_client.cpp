// armada-tpu C++ client implementation: POSIX-socket HTTP/1.1 + a small
// JSON emitter/extractor. See armada_client.hpp for the role this plays
// (the reference Rust client's equivalent, client/rust/src/client.rs).

#include "armada_client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <sstream>

namespace armada {

namespace {

int dial(const std::string& host, int port, int timeout_ms) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res) {
    throw ClientError(0, "cannot resolve " + host);
  }
  int fd = -1;
  for (auto* p = res; p; p = p->ai_next) {
    fd = socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv;
    tv.tv_sec = timeout_ms / 1000;
    tv.tv_usec = (timeout_ms % 1000) * 1000;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    if (connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) throw ClientError(0, "cannot connect to " + host + ":" + port_s);
  return fd;
}

void send_all(int fd, const std::string& data) {
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = send(fd, data.data() + off, data.size() - off, 0);
    if (n <= 0) throw ClientError(0, "send failed");
    off += static_cast<size_t>(n);
  }
}

std::string recv_all(int fd) {
  std::string out;
  char buf[8192];
  for (;;) {
    ssize_t n = recv(fd, buf, sizeof buf, 0);
    if (n < 0) throw ClientError(0, "recv failed or timed out");
    if (n == 0) break;
    out.append(buf, static_cast<size_t>(n));
    // Stop once headers + declared body arrived (Connection: close servers
    // also just close, handled by n==0).
    auto hdr_end = out.find("\r\n\r\n");
    if (hdr_end != std::string::npos) {
      auto cl = out.find("Content-Length:");
      if (cl != std::string::npos && cl < hdr_end) {
        size_t len = std::strtoul(out.c_str() + cl + 15, nullptr, 10);
        if (out.size() >= hdr_end + 4 + len) break;
      }
    }
  }
  return out;
}

std::string b64(const std::string& in) {
  static const char tbl[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  size_t i = 0;
  while (i + 2 < in.size()) {
    unsigned v = (unsigned char)in[i] << 16 | (unsigned char)in[i + 1] << 8 |
                 (unsigned char)in[i + 2];
    out += tbl[v >> 18];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += tbl[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    unsigned v = (unsigned char)in[i] << 16;
    out += tbl[v >> 18];
    out += tbl[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    unsigned v = (unsigned char)in[i] << 16 | (unsigned char)in[i + 1] << 8;
    out += tbl[v >> 18];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += "=";
  }
  return out;
}

// Skip a JSON value starting at i; returns one past its end. Handles
// strings (with escapes), nested objects/arrays, and scalars.
size_t skip_value(const std::string& s, size_t i) {
  while (i < s.size() && std::isspace((unsigned char)s[i])) i++;
  if (i >= s.size()) return i;
  char c = s[i];
  if (c == '"') {
    i++;
    while (i < s.size() && s[i] != '"') {
      if (s[i] == '\\') i++;
      i++;
    }
    return i + 1;
  }
  if (c == '{' || c == '[') {
    char open = c, close = (c == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    for (; i < s.size(); i++) {
      if (in_str) {
        if (s[i] == '\\')
          i++;
        else if (s[i] == '"')
          in_str = false;
        continue;
      }
      if (s[i] == '"') in_str = true;
      else if (s[i] == open) depth++;
      else if (s[i] == close && --depth == 0) return i + 1;
    }
    return i;
  }
  while (i < s.size() && !std::strchr(",}] \t\r\n", s[i])) i++;
  return i;
}

// Find the value position of `"key":` at the top level of the outermost
// object in `body`. Returns npos if absent.
size_t find_key(const std::string& body, const std::string& key) {
  const std::string needle = "\"" + key + "\"";
  size_t start = body.find('{');
  if (start == std::string::npos) return std::string::npos;
  size_t i = start + 1;
  while (i < body.size()) {
    while (i < body.size() && (std::isspace((unsigned char)body[i]) || body[i] == ',')) i++;
    if (i >= body.size() || body[i] == '}') return std::string::npos;
    // at a key string
    size_t key_start = i;
    size_t key_end = skip_value(body, i);
    std::string k = body.substr(key_start, key_end - key_start);
    i = key_end;
    while (i < body.size() && (std::isspace((unsigned char)body[i]) || body[i] == ':')) i++;
    if (k == needle) return i;
    i = skip_value(body, i);
  }
  return std::string::npos;
}

std::string unquote(const std::string& raw) {
  if (raw.size() < 2 || raw.front() != '"') return raw;
  std::string out;
  for (size_t i = 1; i + 1 < raw.size(); i++) {
    if (raw[i] == '\\' && i + 2 < raw.size()) {
      i++;
      switch (raw[i]) {
        case 'n': out += '\n'; break;
        case 't': out += '\t'; break;
        default: out += raw[i];
      }
    } else {
      out += raw[i];
    }
  }
  return out;
}

}  // namespace

namespace json {

std::string quote(const std::string& s) {
  std::string out = "\"";
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default: out += c;
    }
  }
  return out + "\"";
}

std::optional<std::string> get_string(const std::string& body,
                                      const std::string& key) {
  size_t pos = find_key(body, key);
  if (pos == std::string::npos) return std::nullopt;
  size_t end = skip_value(body, pos);
  return unquote(body.substr(pos, end - pos));
}

std::optional<double> get_number(const std::string& body,
                                 const std::string& key) {
  size_t pos = find_key(body, key);
  if (pos == std::string::npos) return std::nullopt;
  size_t end = skip_value(body, pos);
  try {
    return std::stod(body.substr(pos, end - pos));
  } catch (...) {
    return std::nullopt;
  }
}

static std::vector<std::string> array_elements(const std::string& body,
                                               const std::string& key) {
  std::vector<std::string> out;
  size_t pos = find_key(body, key);
  if (pos == std::string::npos || body[pos] != '[') return out;
  size_t i = pos + 1;
  while (i < body.size()) {
    while (i < body.size() && (std::isspace((unsigned char)body[i]) || body[i] == ',')) i++;
    if (i >= body.size() || body[i] == ']') break;
    size_t end = skip_value(body, i);
    out.push_back(body.substr(i, end - i));
    i = end;
  }
  return out;
}

std::vector<std::string> get_string_array(const std::string& body,
                                          const std::string& key) {
  std::vector<std::string> out;
  for (auto& raw : array_elements(body, key)) out.push_back(unquote(raw));
  return out;
}

std::vector<std::string> get_object_array(const std::string& body,
                                          const std::string& key) {
  return array_elements(body, key);
}

}  // namespace json

// ---- builder ----

ClientBuilder& ClientBuilder::basic_auth(const std::string& user,
                                         const std::string& pass) {
  auth_header_ = "Authorization: Basic " + b64(user + ":" + pass);
  return *this;
}

Client ClientBuilder::build() const {
  Client c;
  c.host_ = host_;
  c.port_ = port_;
  c.auth_header_ = auth_header_;
  c.timeout_ms_ = timeout_ms_;
  return c;
}

// ---- transport ----

HttpResponse Client::request(const std::string& method, const std::string& path,
                             const std::string& body,
                             const std::string& content_type,
                             const std::string& accept) {
  int fd = dial(host_, port_, timeout_ms_);
  std::ostringstream req;
  req << method << " " << path << " HTTP/1.1\r\n"
      << "Host: " << host_ << ":" << port_ << "\r\n"
      << "Connection: close\r\n"
      << "Content-Type: " << content_type << "\r\n";
  if (!accept.empty()) req << "Accept: " << accept << "\r\n";
  if (!auth_header_.empty()) req << auth_header_ << "\r\n";
  req << "Content-Length: " << body.size() << "\r\n\r\n" << body;
  try {
    send_all(fd, req.str());
    std::string raw = recv_all(fd);
    close(fd);
    HttpResponse resp;
    if (raw.rfind("HTTP/1.", 0) == 0 && raw.size() > 12) {
      resp.status = std::atoi(raw.c_str() + 9);
    }
    auto hdr_end = raw.find("\r\n\r\n");
    resp.body = hdr_end == std::string::npos ? "" : raw.substr(hdr_end + 4);
    if (resp.status >= 400) {
      auto msg = json::get_string(resp.body, "error");
      throw ClientError(resp.status, msg.value_or(resp.body));
    }
    return resp;
  } catch (...) {
    close(fd);
    throw;
  }
}

// ---- API surface ----

void Client::create_queue(const std::string& name, double priority_factor) {
  std::ostringstream b;
  b << "{\"name\":" << json::quote(name)
    << ",\"priority_factor\":" << priority_factor << "}";
  request("POST", "/api/v1/queue", b.str());
}

QueueInfo Client::get_queue(const std::string& name) {
  auto resp = request("GET", "/api/v1/queue/" + name, "");
  QueueInfo q;
  q.name = json::get_string(resp.body, "name").value_or(name);
  q.priority_factor = json::get_number(resp.body, "priority_factor").value_or(1.0);
  q.cordoned = resp.body.find("\"cordoned\": true") != std::string::npos ||
               resp.body.find("\"cordoned\":true") != std::string::npos;
  return q;
}

std::vector<QueueInfo> Client::list_queues() {
  auto resp = request("GET", "/api/v1/queues", "");
  std::vector<QueueInfo> out;
  for (auto& obj : json::get_object_array(resp.body, "queues")) {
    QueueInfo q;
    q.name = json::get_string(obj, "name").value_or("");
    q.priority_factor = json::get_number(obj, "priority_factor").value_or(1.0);
    out.push_back(q);
  }
  return out;
}

void Client::delete_queue(const std::string& name) {
  request("DELETE", "/api/v1/queue/" + name, "");
}

std::vector<std::string> Client::submit_jobs(
    const std::string& queue, const std::string& jobset,
    const std::vector<JobSubmitItem>& jobs) {
  std::ostringstream b;
  b << "{\"queue\":" << json::quote(queue)
    << ",\"jobset\":" << json::quote(jobset) << ",\"jobs\":[";
  for (size_t i = 0; i < jobs.size(); i++) {
    const auto& j = jobs[i];
    if (i) b << ",";
    b << "{\"id\":" << json::quote(j.id) << ",\"priority\":" << j.priority;
    if (!j.priority_class.empty())
      b << ",\"priority_class\":" << json::quote(j.priority_class);
    b << ",\"requests\":{";
    bool first = true;
    for (const auto& [k, v] : j.requests) {
      if (!first) b << ",";
      first = false;
      b << json::quote(k) << ":" << json::quote(v);
    }
    b << "}";
    auto emit_map = [&](const char* key,
                        const std::map<std::string, std::string>& m) {
      if (m.empty()) return;
      b << ",\"" << key << "\":{";
      bool f = true;
      for (const auto& [k, v] : m) {
        if (!f) b << ",";
        f = false;
        b << json::quote(k) << ":" << json::quote(v);
      }
      b << "}";
    };
    emit_map("annotations", j.annotations);
    emit_map("node_selector", j.node_selector);
    if (!j.gang_id.empty()) {
      b << ",\"gang\":{\"id\":" << json::quote(j.gang_id)
        << ",\"cardinality\":" << j.gang_cardinality << "}";
    }
    b << "}";
  }
  b << "]}";
  auto resp = request("POST", "/api/v1/job/submit", b.str());
  return json::get_string_array(resp.body, "job_ids");
}

void Client::cancel_jobs(const std::string& queue, const std::string& jobset,
                         const std::vector<std::string>& job_ids,
                         bool cancel_jobset) {
  std::ostringstream b;
  b << "{\"queue\":" << json::quote(queue)
    << ",\"jobset\":" << json::quote(jobset) << ",\"job_ids\":[";
  for (size_t i = 0; i < job_ids.size(); i++) {
    if (i) b << ",";
    b << json::quote(job_ids[i]);
  }
  b << "],\"cancel_jobset\":" << (cancel_jobset ? "true" : "false") << "}";
  request("POST", "/api/v1/job/cancel", b.str());
}

void Client::reprioritize_jobs(const std::string& queue,
                               const std::string& jobset,
                               const std::vector<std::string>& job_ids,
                               long priority) {
  std::ostringstream b;
  b << "{\"queue\":" << json::quote(queue)
    << ",\"jobset\":" << json::quote(jobset) << ",\"job_ids\":[";
  for (size_t i = 0; i < job_ids.size(); i++) {
    if (i) b << ",";
    b << json::quote(job_ids[i]);
  }
  b << "],\"priority\":" << priority << "}";
  request("POST", "/api/v1/job/reprioritize", b.str());
}

std::pair<std::vector<JobSetEvent>, long> Client::get_events(
    const std::string& queue, const std::string& jobset, long from_offset) {
  auto resp = request("GET",
                      "/api/v1/jobset/" + queue + "/" + jobset +
                          "/events?from=" + std::to_string(from_offset),
                      "");
  std::vector<JobSetEvent> events;
  for (auto& obj : json::get_object_array(resp.body, "events")) {
    JobSetEvent e;
    e.offset = static_cast<long>(json::get_number(obj, "offset").value_or(0));
    e.type = json::get_string(obj, "type").value_or("");
    e.job_id = json::get_string(obj, "job_id").value_or("");
    e.created = json::get_number(obj, "created").value_or(0.0);
    events.push_back(e);
  }
  long next = static_cast<long>(json::get_number(resp.body, "next").value_or(from_offset));
  return {events, next};
}

std::string Client::get_jobs_raw(const std::string& query_string) {
  return request("GET", "/api/v1/jobs?" + query_string, "").body;
}

}  // namespace armada
