// Protobuf add-on for the armada-tpu C++ client.
//
// The base library (armada_client.hpp) is dependency-free JSON; this
// translation unit links libprotobuf and speaks the binary wire format
// generated from proto/armada.proto — the same schema every codegen
// client builds against (the reference's generated pkg/api clients,
// client/DotNet, client/java). Submission posts application/x-protobuf
// to the gateway's submit route and parses a JobSubmitResponse.

#pragma once

#include <string>
#include <vector>

#include "armada_client.hpp"

namespace armada {

// Submit via the binary protobuf encoding. Items reuse the JSON client's
// JobSubmitItem struct; they are re-encoded as
// armada_tpu.api.JobSubmitRequest on the wire.
std::vector<std::string> submit_jobs_proto(
    Client& client, const std::string& queue, const std::string& jobset,
    const std::vector<JobSubmitItem>& jobs);

}  // namespace armada
