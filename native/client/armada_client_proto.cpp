// See armada_client_proto.hpp. Builds against the protoc-generated
// armada.pb.{h,cc} (make proto) and full libprotobuf.

#include "armada_client_proto.hpp"

#include "armada.pb.h"

namespace armada {

std::vector<std::string> submit_jobs_proto(
    Client& client, const std::string& queue, const std::string& jobset,
    const std::vector<JobSubmitItem>& jobs) {
  armada_tpu::api::JobSubmitRequest req;
  req.set_queue(queue);
  req.set_jobset(jobset);
  for (const auto& item : jobs) {
    auto* j = req.add_jobs();
    j->set_priority(static_cast<int32_t>(item.priority));
    j->set_priority_class(item.priority_class);
    for (const auto& [name, qty] : item.requests) {
      (*j->mutable_requests())[name] = qty;
    }
    for (const auto& [key, value] : item.annotations) {
      (*j->mutable_annotations())[key] = value;
    }
    for (const auto& [key, value] : item.node_selector) {
      (*j->mutable_node_selector())[key] = value;
    }
    if (!item.gang_id.empty()) {
      j->mutable_gang()->set_id(item.gang_id);
      j->mutable_gang()->set_cardinality(
          static_cast<uint32_t>(item.gang_cardinality));
    }
  }
  auto resp = client.request("POST", "/api/v1/job/submit",
                             req.SerializeAsString(),
                             "application/x-protobuf",
                             "application/x-protobuf");
  armada_tpu::api::JobSubmitResponse out;
  if (!out.ParseFromString(resp.body)) {
    throw ClientError(resp.status, "cannot parse JobSubmitResponse");
  }
  return {out.job_ids().begin(), out.job_ids().end()};
}

}  // namespace armada
