// armada-tpu C++ client library.
//
// Plays the role of the reference's Rust client
// (/root/reference/client/rust/src/{client.rs,builder.rs,auth.rs}):
// a native client with a connection builder, pluggable auth (basic
// credentials or a bearer token, auth.rs), and the full job surface —
// queue CRUD, submit, cancel, reprioritize, job queries and jobset event
// watching. Transport is the control plane's REST/JSON gateway
// (services/rest_gateway.py — the grpc-gateway analogue), spoken over a
// dependency-free HTTP/1.1 implementation (plain POSIX sockets), so the
// library builds with nothing beyond a C++17 toolchain.

#pragma once

#include <map>
#include <optional>
#include <stdexcept>
#include <string>
#include <vector>

namespace armada {

struct HttpResponse {
  int status = 0;
  std::string body;
};

class ClientError : public std::runtime_error {
 public:
  ClientError(int status, const std::string& message)
      : std::runtime_error(message), status(status) {}
  int status;
};

// One resource request entry, e.g. {"cpu", "1"} / {"memory", "4Gi"}.
using ResourceMap = std::map<std::string, std::string>;

struct JobSubmitItem {
  std::string id;  // empty -> server-assigned
  ResourceMap requests;
  std::string priority_class;
  long priority = 0;
  std::map<std::string, std::string> annotations;
  std::map<std::string, std::string> node_selector;
  // Gang membership (id empty -> none).
  std::string gang_id;
  int gang_cardinality = 0;
};

struct QueueInfo {
  std::string name;
  double priority_factor = 1.0;
  bool cordoned = false;
};

struct JobSetEvent {
  long offset = 0;
  std::string type;
  std::string job_id;
  double created = 0.0;
};

// Connection + auth builder (client/rust/src/builder.rs).
class ClientBuilder;

class Client {
 public:
  // ---- queue CRUD ----
  void create_queue(const std::string& name, double priority_factor = 1.0);
  QueueInfo get_queue(const std::string& name);
  std::vector<QueueInfo> list_queues();
  void delete_queue(const std::string& name);

  // ---- jobs ----
  std::vector<std::string> submit_jobs(const std::string& queue,
                                       const std::string& jobset,
                                       const std::vector<JobSubmitItem>& jobs);
  void cancel_jobs(const std::string& queue, const std::string& jobset,
                   const std::vector<std::string>& job_ids,
                   bool cancel_jobset = false);
  void reprioritize_jobs(const std::string& queue, const std::string& jobset,
                         const std::vector<std::string>& job_ids,
                         long priority);

  // Jobset events from `from_offset`; returns events + the next offset
  // (the watch loop of client.rs: poll with the returned cursor).
  std::pair<std::vector<JobSetEvent>, long> get_events(
      const std::string& queue, const std::string& jobset, long from_offset);

  // Raw query passthrough: /api/v1/jobs?... (returns the JSON body).
  std::string get_jobs_raw(const std::string& query_string);

  // Low-level request (exposed for tests and extensions — e.g. the
  // protobuf add-on in armada_client_proto.cpp sends
  // application/x-protobuf bodies through it).
  HttpResponse request(const std::string& method, const std::string& path,
                       const std::string& body,
                       const std::string& content_type = "application/json",
                       const std::string& accept = "");

 private:
  friend class ClientBuilder;
  std::string host_;
  int port_ = 0;
  std::string auth_header_;  // full "Authorization: ..." line or empty
  int timeout_ms_ = 30000;
};

class ClientBuilder {
 public:
  ClientBuilder& target(const std::string& host, int port) {
    host_ = host;
    port_ = port;
    return *this;
  }
  // auth.rs: basic credentials...
  ClientBuilder& basic_auth(const std::string& user, const std::string& pass);
  // ...or an OIDC-shaped bearer token.
  ClientBuilder& bearer_token(const std::string& token) {
    auth_header_ = "Authorization: Bearer " + token;
    return *this;
  }
  ClientBuilder& timeout_ms(int ms) {
    timeout_ms_ = ms;
    return *this;
  }
  Client build() const;

 private:
  std::string host_ = "127.0.0.1";
  int port_ = 0;
  std::string auth_header_;
  int timeout_ms_ = 30000;
};

// ---- minimal JSON helpers (exposed for reuse by callers) ----
namespace json {
std::string quote(const std::string& s);
// Extract "key": "value" | number | bool at the top level of an object
// (flat extraction; sufficient for the gateway's response shapes).
std::optional<std::string> get_string(const std::string& body,
                                      const std::string& key);
std::optional<double> get_number(const std::string& body,
                                 const std::string& key);
// All string elements of the array under `key` (e.g. job_ids).
std::vector<std::string> get_string_array(const std::string& body,
                                          const std::string& key);
// All object elements of the array under `key`, as raw JSON strings.
std::vector<std::string> get_object_array(const std::string& body,
                                          const std::string& key);
}  // namespace json

}  // namespace armada
