// End-to-end exercise of the C++ client against a live control plane:
// create a queue, submit jobs (one gang), watch events to completion,
// query rows, cancel a straggler. Exits 0 on success; prints a reason and
// exits 1 otherwise. Driven by tests/test_cpp_client.py.

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <set>
#include <thread>

#include "armada_client.hpp"

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: client_demo HOST PORT [TOKEN]\n");
    return 2;
  }
  try {
    armada::ClientBuilder builder;
    builder.target(argv[1], std::atoi(argv[2]));
    if (argc > 3) builder.bearer_token(argv[3]);
    auto client = builder.build();

    client.create_queue("cpp-team", 1.0);
    auto q = client.get_queue("cpp-team");
    if (q.name != "cpp-team") throw std::runtime_error("get_queue mismatch");
    if (client.list_queues().empty()) throw std::runtime_error("no queues");

    std::vector<armada::JobSubmitItem> jobs;
    for (int i = 0; i < 3; i++) {
      armada::JobSubmitItem j;
      j.id = "cpp-job-" + std::to_string(i);
      j.requests = {{"cpu", "1"}, {"memory", "1Gi"}};
      jobs.push_back(j);
    }
    armada::JobSubmitItem g0, g1;
    g0.id = "cpp-gang-0";
    g1.id = "cpp-gang-1";
    g0.requests = g1.requests = {{"cpu", "1"}, {"memory", "1Gi"}};
    g0.gang_id = g1.gang_id = "cpp-gang";
    g0.gang_cardinality = g1.gang_cardinality = 2;
    jobs.push_back(g0);
    jobs.push_back(g1);

    auto ids = client.submit_jobs("cpp-team", "cpp-set", jobs);
    if (ids.size() != 5) throw std::runtime_error("expected 5 job ids");

    // Watch until every job succeeds (client.rs-style poll loop).
    std::set<std::string> done;
    long cursor = 0;
    for (int iter = 0; iter < 200 && done.size() < ids.size(); iter++) {
      auto [events, next] = client.get_events("cpp-team", "cpp-set", cursor);
      cursor = next;
      for (const auto& e : events) {
        if (e.type == "JobSucceeded") done.insert(e.job_id);
        if (e.type == "JobErrors")
          throw std::runtime_error("job failed: " + e.job_id);
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    if (done.size() < ids.size())
      throw std::runtime_error("timeout: only " + std::to_string(done.size()) +
                               " of 5 jobs finished");

    auto rows = client.get_jobs_raw("queue=cpp-team&state=succeeded");
    if (rows.find("cpp-job-0") == std::string::npos)
      throw std::runtime_error("query missing cpp-job-0");

    std::printf("cpp client e2e ok: %zu jobs succeeded\n", done.size());
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "cpp client e2e failed: %s\n", e.what());
    return 1;
  }
}
