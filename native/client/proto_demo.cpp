// Protobuf wire-format demo: submit over application/x-protobuf and
// verify through the JSON query surface. Driven by
// tests/test_cpp_client.py against a live control plane.
//
// Usage: proto_demo HOST PORT

#include <chrono>
#include <cstdlib>
#include <thread>
#include <iostream>

#include "armada_client.hpp"
#include "armada_client_proto.hpp"

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: proto_demo HOST PORT\n";
    return 2;
  }
  const std::string host = argv[1];
  const int port = std::atoi(argv[2]);
  try {
    armada::Client client =
        armada::ClientBuilder().target(host, port).build();
    client.create_queue("cpp-proto", 1.0);

    std::vector<armada::JobSubmitItem> jobs(2);
    jobs[0].requests = {{"cpu", "1"}, {"memory", "1Gi"}};
    jobs[0].priority = 1;
    jobs[0].annotations = {{"encoding", "protobuf"}};
    jobs[1].requests = {{"cpu", "2"}, {"memory", "2Gi"}};
    jobs[1].priority = 2;

    auto ids = armada::submit_jobs_proto(client, "cpp-proto", "pset", jobs);
    if (ids.size() != 2) {
      std::cerr << "expected 2 job ids, got " << ids.size() << "\n";
      return 1;
    }
    for (const auto& id : ids) std::cout << "submitted " << id << "\n";

    // Cross-encoding check: the JSON query surface sees proto
    // submissions (ingestion lands on the next scheduler cycle; retry).
    bool visible = false;
    for (int attempt = 0; attempt < 40 && !visible; attempt++) {
      auto body = client.get_jobs_raw("queue=cpp-proto&take=10");
      visible = true;
      for (const auto& id : ids) {
        if (body.find(id) == std::string::npos) visible = false;
      }
      if (!visible) {
        std::this_thread::sleep_for(std::chrono::milliseconds(100));
      }
    }
    if (!visible) {
      std::cerr << "proto-submitted jobs missing from JSON query\n";
      return 1;
    }
    std::cout << "proto-submitted jobs visible over JSON query\n";
    std::cout << "OK\n";
    return 0;
  } catch (const armada::ClientError& e) {
    std::cerr << "client error " << e.status << ": " << e.what() << "\n";
    return 1;
  }
}
