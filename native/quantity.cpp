// _armada_native: C++ hot paths for host-side snapshot encoding.
//
// The per-round snapshot builder converts hundreds of thousands of
// Kubernetes quantity strings ("100m", "16Gi", "2e3") into scaled int64
// columns. The reference does this in Go with k8s resource.Quantity
// (internal/scheduler/internaltypes/resource_list_factory.go); the Python
// Fraction path is exact but ~50us per value. This extension parses with
// exact __int128 arithmetic at ~50ns per value.
//
// Exposed functions (CPython API, no external deps):
//   parse_quantity(str, scale:int, ceil:bool) -> int
//   parse_quantities(list, scale:int, ceil:bool) -> bytes (int64 LE array)
//   encode_requests(jobs: list[dict], names: list[str], scales: list[int],
//                   ceil: bool) -> bytes (int64 LE, row-major [J, R])

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>

namespace {

typedef __int128 i128;

const int64_t I64_MAX = INT64_MAX;
const int64_t I64_MIN = INT64_MIN;

struct ParseResult {
  bool ok = false;
  // value = mantissa * 10^dec_exp * 2^bin_exp, mantissa exact.
  i128 mantissa = 0;
  int dec_exp = 0;
  int bin_exp = 0;
};

// Parse [sign]digits[.digits][e|E exp | suffix]
ParseResult parse_decimal(const char* s, Py_ssize_t n) {
  ParseResult r;
  Py_ssize_t i = 0;
  bool neg = false;
  if (i < n && (s[i] == '+' || s[i] == '-')) {
    neg = s[i] == '-';
    i++;
  }
  i128 mant = 0;
  int frac_digits = 0;
  bool any_digit = false, in_frac = false;
  for (; i < n; i++) {
    char c = s[i];
    if (c >= '0' && c <= '9') {
      any_digit = true;
      if (mant < (i128)1 << 100) {  // cap; beyond this precision irrelevant
        mant = mant * 10 + (c - '0');
        if (in_frac) frac_digits++;
      } else if (!in_frac) {
        r.dec_exp++;  // overflow of integer part: scale up
      }
    } else if (c == '.' && !in_frac) {
      in_frac = true;
    } else {
      break;
    }
  }
  if (!any_digit) return r;
  r.dec_exp -= frac_digits;

  // Suffix or exponent.
  if (i < n) {
    char c = s[i];
    Py_ssize_t rem = n - i;
    auto is_last = [&](Py_ssize_t k) { return i + k == n; };
    if ((c == 'e' || c == 'E') && rem >= 2 &&
        ((s[i + 1] >= '0' && s[i + 1] <= '9') || s[i + 1] == '+' ||
         s[i + 1] == '-')) {
      // scientific notation
      i++;
      bool eneg = false;
      if (s[i] == '+' || s[i] == '-') {
        eneg = s[i] == '-';
        i++;
      }
      int ev = 0;
      for (; i < n && s[i] >= '0' && s[i] <= '9'; i++) {
        if (ev < 1000000) ev = ev * 10 + (s[i] - '0');  // clamp: no wrap UB
      }
      if (i != n) return r;
      r.dec_exp += eneg ? -ev : ev;
    } else if (rem == 2 && s[i + 1] == 'i') {
      int p = 0;
      switch (c) {
        case 'K': p = 10; break;
        case 'M': p = 20; break;
        case 'G': p = 30; break;
        case 'T': p = 40; break;
        case 'P': p = 50; break;
        case 'E': p = 60; break;
        default: return r;
      }
      r.bin_exp = p;
    } else if (rem == 1) {
      switch (c) {
        case 'n': r.dec_exp += -9; break;
        case 'u': r.dec_exp += -6; break;
        case 'm': r.dec_exp += -3; break;
        case 'k': r.dec_exp += 3; break;
        case 'M': r.dec_exp += 6; break;
        case 'G': r.dec_exp += 9; break;
        case 'T': r.dec_exp += 12; break;
        case 'P': r.dec_exp += 15; break;
        case 'E': r.dec_exp += 18; break;
        default: return r;
      }
    } else {
      return r;
    }
  }
  r.mantissa = neg ? -mant : mant;
  r.ok = true;
  return r;
}

// value / 10^scale with ceil/floor rounding, exact, saturating to int64.
int64_t scale_value(const ParseResult& p, int scale, bool ceil_mode, bool* ok) {
  *ok = true;
  i128 num = p.mantissa;
  int dec = p.dec_exp - scale;
  int bin = p.bin_exp;
  // numerator = mant * 2^bin * 10^max(dec,0); denominator = 10^max(-dec,0)
  i128 den = 1;
  while (dec > 0) {
    if (num > ((i128)1 << 126) / 10 || num < -((i128)1 << 126) / 10) {
      *ok = true;
      return num > 0 ? I64_MAX : I64_MIN;  // saturate
    }
    num *= 10;
    dec--;
  }
  while (dec < 0) {
    den *= 10;
    dec++;
    if (den > ((i128)1 << 120)) break;  // value underflows to 0/1 anyway
  }
  while (bin > 0) {
    if (num > ((i128)1 << 125) || num < -((i128)1 << 125)) {
      return num > 0 ? I64_MAX : I64_MIN;
    }
    num <<= 1;
    bin--;
  }
  i128 q = num / den;
  i128 rem = num % den;
  if (rem != 0) {
    if (ceil_mode && num > 0) q += 1;
    if (!ceil_mode && num < 0) q -= 1;
  }
  if (q > I64_MAX) return I64_MAX;
  if (q < I64_MIN) return I64_MIN;
  return (int64_t)q;
}

bool parse_via_str(PyObject* obj, int scale, bool ceil_mode, int64_t* out) {
  // Route through str() for the same semantics as Fraction(str(x)); the
  // decimal parser keeps ~30 significant digits exactly (mantissa cap),
  // which covers every value that doesn't saturate int64 after scaling.
  PyObject* s = PyObject_Str(obj);
  if (!s) return false;
  Py_ssize_t n;
  const char* c = PyUnicode_AsUTF8AndSize(s, &n);
  ParseResult p = parse_decimal(c, n);
  Py_DECREF(s);
  if (!p.ok) return false;
  bool ok;
  *out = scale_value(p, scale, ceil_mode, &ok);
  return ok;
}

bool parse_obj(PyObject* obj, int scale, bool ceil_mode, int64_t* out) {
  if (PyLong_Check(obj)) {
    ParseResult p;
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (overflow) {
      // Bigger than int64: go through the exact string path so coarse
      // scales still produce the exact scaled value.
      return parse_via_str(obj, scale, ceil_mode, out);
    }
    p.mantissa = v;
    p.ok = true;
    bool ok;
    *out = scale_value(p, scale, ceil_mode, &ok);
    return ok;
  }
  if (PyFloat_Check(obj)) {
    return parse_via_str(obj, scale, ceil_mode, out);
  }
  if (PyUnicode_Check(obj)) {
    Py_ssize_t n;
    const char* c = PyUnicode_AsUTF8AndSize(obj, &n);
    // strip whitespace (any, like str.strip())
    while (n > 0 && isspace((unsigned char)*c)) { c++; n--; }
    while (n > 0 && isspace((unsigned char)c[n - 1])) n--;
    ParseResult p = parse_decimal(c, n);
    if (!p.ok) return false;
    bool ok;
    *out = scale_value(p, scale, ceil_mode, &ok);
    return ok;
  }
  // numpy integer scalars and other index-able types
  if (PyIndex_Check(obj)) {
    PyObject* as_int = PyNumber_Index(obj);
    if (!as_int) {
      PyErr_Clear();
      return false;
    }
    bool ok = parse_obj(as_int, scale, ceil_mode, out);
    Py_DECREF(as_int);
    return ok;
  }
  return false;
}

PyObject* py_parse_quantity(PyObject*, PyObject* args) {
  PyObject* obj;
  int scale, ceil_mode;
  if (!PyArg_ParseTuple(args, "Oip", &obj, &scale, &ceil_mode)) return nullptr;
  int64_t out;
  if (!parse_obj(obj, scale, ceil_mode != 0, &out)) {
    PyErr_Format(PyExc_ValueError, "invalid quantity: %R", obj);
    return nullptr;
  }
  return PyLong_FromLongLong(out);
}

PyObject* py_parse_quantities(PyObject*, PyObject* args) {
  PyObject* seq;
  int scale, ceil_mode;
  if (!PyArg_ParseTuple(args, "Oip", &seq, &scale, &ceil_mode)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "expected a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject* bytes = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (!bytes) {
    Py_DECREF(fast);
    return nullptr;
  }
  int64_t* out = (int64_t*)PyBytes_AS_STRING(bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast, i);
    if (!parse_obj(item, scale, ceil_mode != 0, &out[i])) {
      PyErr_Format(PyExc_ValueError, "invalid quantity at %zd: %R", i, item);
      Py_DECREF(fast);
      Py_DECREF(bytes);
      return nullptr;
    }
  }
  Py_DECREF(fast);
  return bytes;
}

// encode_requests(jobs, names, scales, ceil) -> bytes int64[J, R]
// jobs: sequence of dicts {resource-name: quantity}
PyObject* py_encode_requests(PyObject*, PyObject* args) {
  PyObject *jobs, *names, *scales;
  int ceil_mode;
  if (!PyArg_ParseTuple(args, "OOOp", &jobs, &names, &scales, &ceil_mode))
    return nullptr;
  PyObject* jobs_fast = PySequence_Fast(jobs, "jobs must be a sequence");
  if (!jobs_fast) return nullptr;
  PyObject* names_fast = PySequence_Fast(names, "names must be a sequence");
  if (!names_fast) {
    Py_DECREF(jobs_fast);
    return nullptr;
  }
  PyObject* scales_fast = PySequence_Fast(scales, "scales must be a sequence");
  if (!scales_fast) {
    Py_DECREF(jobs_fast);
    Py_DECREF(names_fast);
    return nullptr;
  }
  Py_ssize_t J = PySequence_Fast_GET_SIZE(jobs_fast);
  Py_ssize_t R = PySequence_Fast_GET_SIZE(names_fast);
  PyObject* bytes = PyBytes_FromStringAndSize(nullptr, J * R * 8);
  if (!bytes) goto fail;
  {
    int64_t* out = (int64_t*)PyBytes_AS_STRING(bytes);
    memset(out, 0, J * R * 8);
    for (Py_ssize_t j = 0; j < J; j++) {
      PyObject* d = PySequence_Fast_GET_ITEM(jobs_fast, j);
      if (!PyDict_Check(d)) {
        if (d == Py_None) continue;
        PyErr_SetString(PyExc_TypeError, "each job must be a dict or None");
        Py_DECREF(bytes);
        goto fail;
      }
      if (PyDict_GET_SIZE(d) == 0) continue;
      for (Py_ssize_t r = 0; r < R; r++) {
        PyObject* name = PySequence_Fast_GET_ITEM(names_fast, r);
        PyObject* v = PyDict_GetItem(d, name);  // borrowed
        if (v == nullptr) continue;
        long scale = PyLong_AsLong(PySequence_Fast_GET_ITEM(scales_fast, r));
        int64_t val;
        if (!parse_obj(v, (int)scale, ceil_mode != 0, &val)) {
          PyErr_Format(PyExc_ValueError, "job %zd: invalid quantity %R", j, v);
          Py_DECREF(bytes);
          goto fail;
        }
        out[j * R + r] = val;
      }
    }
  }
  Py_DECREF(jobs_fast);
  Py_DECREF(names_fast);
  Py_DECREF(scales_fast);
  return bytes;
fail:
  Py_DECREF(jobs_fast);
  Py_DECREF(names_fast);
  Py_DECREF(scales_fast);
  return nullptr;
}

PyMethodDef methods[] = {
    {"parse_quantity", py_parse_quantity, METH_VARARGS,
     "parse_quantity(value, scale, ceil) -> int64"},
    {"parse_quantities", py_parse_quantities, METH_VARARGS,
     "parse_quantities(seq, scale, ceil) -> bytes of int64"},
    {"encode_requests", py_encode_requests, METH_VARARGS,
     "encode_requests(jobs, names, scales, ceil) -> bytes of int64[J,R]"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "_armada_native",
                      "C++ hot paths for snapshot encoding", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__armada_native(void) { return PyModule_Create(&module); }
