"""Build the _armada_native C++ extension in-place:

    cd native && python setup.py build_ext --inplace
    (or: make -C native)

The built module is copied next to armada_tpu/ so `import _armada_native`
resolves; armada_tpu.core.resources falls back to the exact-Fraction Python
path when it is absent.
"""

from setuptools import Extension, setup

setup(
    name="armada-tpu-native",
    ext_modules=[
        Extension(
            "_armada_native",
            sources=["quantity.cpp"],
            extra_compile_args=["-O3", "-std=c++17"],
        )
    ],
)
