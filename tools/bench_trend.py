"""Print the bench trajectory across every checked-in BENCH_r*.json.

    python tools/bench_trend.py [--dir REPO]

One row per artifact — warm headline, tracking_100k and burst_50k cycle
times, the solve share of the warm cycle, the effective solver
parameters (hot window / chunk, starred when a BENCH_TUNED profile
supplied them), and the residency column (snapshot mode that carried
the warm cycle + the MB it uploaded) — tolerant of every historical
schema (BENCH_r03.json has no `parsed` block; burst_50k only
exists from r05): a metric an artifact does not carry prints as "-",
and an artifact nothing can be recovered from still gets a row.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from bench_gate import REPO, _round_num, extract_metrics, parse_artifact  # noqa: E402


def _fmt(v) -> str:
    return f"{v:.3f}" if isinstance(v, (int, float)) else "-"


def _human_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "K", "M", "G", "T"):
        if abs(n) < 1024 or unit == "T":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}T"  # pragma: no cover - loop always returns


def rows(search_dir: str) -> list[dict]:
    out = []
    for path in sorted(
        glob.glob(os.path.join(search_dir, "BENCH_r*.json")), key=_round_num
    ):
        row = {"round": os.path.basename(path), "warm": None,
               "tracking": None, "burst": None, "solve": None,
               "trace": False, "params": None, "whatif": None,
               "frontdoor": None, "transfer": None, "fairness": None,
               "policy": None, "residency": None, "kernels": None}
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            row["round"] += " (unreadable)"
            out.append(row)
            continue
        result = parse_artifact(doc)
        row.update(extract_metrics(result))
        extra = result.get("extra") if isinstance(result, dict) else None
        if isinstance(extra, dict) and isinstance(
            extra.get("solve_s"), (int, float)
        ):
            row["solve"] = float(extra["solve_s"])
        if isinstance(extra, dict) and extra.get("trace_path"):
            # The run recorded a flight-recorder bundle (BENCH_TRACE):
            # this artifact's workload is replayable by
            # tools/replay_gate.py against any candidate kernel.
            row["trace"] = True
        whatif = extra.get("whatif") if isinstance(extra, dict) else None
        if isinstance(whatif, dict):
            # What-if planner block (armada_tpu/whatif): artifacts from
            # runs that shadow-solved plans carry plan count + median
            # plan wall clock; earlier artifacts simply lack the block.
            plans = whatif.get("plans")
            plan_s = whatif.get("plan_s")
            row["whatif"] = (
                f"{plans}@{plan_s:.2f}s"
                if isinstance(plans, int) and isinstance(plan_s, (int, float))
                else "yes"
            )
        frontdoor = extra.get("frontdoor") if isinstance(extra, dict) else None
        if isinstance(frontdoor, dict):
            # Front-door SLO block (tools/frontdoor_soak.py --out):
            # worst-seed submit p99 + max shard ingest lag; a "!" marks
            # a run whose soak breached its gate. Old artifacts simply
            # lack the block.
            p99 = frontdoor.get("p99_ms")
            lag = frontdoor.get("max_lag")
            row["frontdoor"] = (
                (
                    f"{p99:.0f}ms/{lag}"
                    if isinstance(p99, (int, float)) and isinstance(lag, int)
                    else "yes"
                )
                + ("" if frontdoor.get("ok", True) else "!")
            )
        transfer = extra.get("transfer") if isinstance(extra, dict) else None
        if isinstance(transfer, dict):
            # Round-observatory cost ledger (armada_tpu/observe): the
            # headline warm cycle's bytes up/down plus its compile
            # count ("c0" is the healthy warm state). Older artifacts
            # simply lack the block.
            up = transfer.get("bytes_up")
            down = transfer.get("bytes_down")
            compiles = (transfer.get("compiles") or {}).get("compiles")
            if isinstance(up, (int, float)) and isinstance(down, (int, float)):
                # One whitespace-free token so column positions stay
                # parseable: up/down,cN (c = warm-cycle compile count).
                row["transfer"] = (
                    f"{_human_bytes(up)}/{_human_bytes(down)}"
                    + (
                        f",c{compiles:.0f}"
                        if isinstance(compiles, (int, float))
                        else ""
                    )
                )
            else:
                row["transfer"] = "yes"
        residency = extra.get("residency") if isinstance(extra, dict) else None
        if isinstance(residency, dict):
            # Device-resident round state (armada_tpu/snapshot/residency):
            # which snapshot path carried the headline warm cycle (delta
            # scatter sync vs full reset upload) + the MB it uploaded,
            # as one token mode@MBup. Older artifacts (and BENCH_RESIDENT=0
            # runs) simply lack the block and print "-".
            mode = residency.get("mode")
            up = residency.get("bytes_up")
            row["residency"] = (
                f"{mode}@{float(up) / 1e6:.1f}MB"
                if isinstance(mode, str) and isinstance(up, (int, float))
                else (mode or "yes")
            )
        fairness = extra.get("fairness") if isinstance(extra, dict) else None
        if isinstance(fairness, dict):
            # Fairness-observatory block (armada_tpu/observe/fairness.py):
            # the headline cycle's Jain index + max fairness regret as
            # one token, jJAIN/rREGRET. Older artifacts simply lack the
            # block and print "-".
            jain = fairness.get("jain")
            regret = fairness.get("max_regret")
            row["fairness"] = (
                f"j{jain:.3f}/r{regret:.3f}"
                if isinstance(jain, (int, float))
                and isinstance(regret, (int, float))
                else "yes"
            )
            # Active fairness policy (pre-policy artifacts lack the key
            # and print "-"): a trend break across a flip must be
            # attributable to the objective change, not read as a
            # regression.
            pol = fairness.get("policy")
            if isinstance(pol, str) and pol:
                row["policy"] = pol
        kernels = extra.get("kernels") if isinstance(extra, dict) else None
        if isinstance(kernels, dict):
            # Solve-kernel block (armada_tpu/ops/pallas_kernels.py): the
            # path that produced the headline, with the pallas block
            # count when the path runs blocked ("pallas/64b" = 64 node
            # blocks). Pre-kernel artifacts simply lack the block.
            kpath = kernels.get("path")
            blocks = kernels.get("blocks")
            row["kernels"] = (
                f"{kpath}/{blocks}b"
                if isinstance(kpath, str) and isinstance(blocks, int)
                else (kpath or "yes")
            )
        params = extra.get("params") if isinstance(extra, dict) else None
        if isinstance(params, dict):
            # Effective headline solver parameters (window/chunk, "*"
            # when a BENCH_TUNED profile supplied them); artifacts from
            # before the autotune round simply lack the block.
            row["params"] = (
                f"{params.get('hot_window_slots', 0)}"
                f"/{params.get('chunk_loops', 1)}"
                + ("*" if params.get("tuned") else "")
            )
        out.append(row)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=REPO)
    args = ap.parse_args(argv)
    table = rows(args.dir)
    if not table:
        print("no BENCH_r*.json artifacts found")
        return 1
    header = (
        f"{'artifact':<18} {'warm_s':>8} {'solve_s':>8} {'tracking_s':>10} "
        f"{'burst_s':>8} {'win/chunk':>10} {'trace':>6} {'whatif':>9} "
        f"{'frontdoor':>10} {'transfer':>16} {'residency':>14} "
        f"{'fairness':>15} {'policy':>12} {'kernels':>12}"
    )
    print(header)
    print("-" * len(header))
    for r in table:
        print(
            f"{r['round']:<18} {_fmt(r['warm']):>8} {_fmt(r['solve']):>8} "
            f"{_fmt(r['tracking']):>10} {_fmt(r['burst']):>8} "
            f"{r.get('params') or '-':>10} "
            f"{'yes' if r.get('trace') else '-':>6} "
            f"{r.get('whatif') or '-':>9} "
            f"{r.get('frontdoor') or '-':>10} "
            f"{r.get('transfer') or '-':>16} "
            f"{r.get('residency') or '-':>14} "
            f"{r.get('fairness') or '-':>15} "
            f"{r.get('policy') or '-':>12} "
            f"{r.get('kernels') or '-':>12}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
