"""Render docs/architecture.md's "Known gaps" section from the tracked
checklist docs/known_gaps.yaml.

The gaps list rotted twice when it was hand-maintained prose; now the
YAML is the single source of truth and this renderer is deterministic,
so tests/test_docs_gaps.py can assert the doc matches the checklist
byte-for-byte.

  python tools/gen_known_gaps.py           # print the rendered section
  python tools/gen_known_gaps.py --write   # splice it into the doc
  python tools/gen_known_gaps.py --check   # exit 1 on drift
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import textwrap

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
YAML_PATH = os.path.join(REPO, "docs", "known_gaps.yaml")
DOC_PATH = os.path.join(REPO, "docs", "architecture.md")

HEADING = "## Known gaps vs the reference (tracked)"
SECTION_RE = re.compile(r"## Known gaps.*?(?=\n## |\Z)", re.DOTALL)


def load_gaps(path: str = YAML_PATH) -> list[dict]:
    with open(path) as f:
        gaps = yaml.safe_load(f)["gaps"]
    for g in gaps:
        assert g["status"] in ("open", "closed"), g
        assert re.fullmatch(r"[a-z0-9-]+", g["id"]), g
        assert "::" in g["closer"], f"closer must be a pytest node id: {g}"
    assert len({g["id"] for g in gaps}) == len(gaps), "duplicate gap ids"
    return gaps


def _wrap(prefix: str, text: str) -> str:
    # Never split words/hyphens: pytest node ids and `code` spans must
    # survive wrapping intact.
    return textwrap.fill(
        f"{prefix} {text}", width=72, subsequent_indent="  ",
        break_long_words=False, break_on_hyphens=False,
    )


def render(gaps: list[dict]) -> str:
    """The full section, heading through last bullet, no trailing \\n."""
    open_gaps = [g for g in gaps if g["status"] == "open"]
    closed = [g for g in gaps if g["status"] == "closed"]
    out = [
        HEADING,
        "",
        textwrap.fill(
            "Generated from `docs/known_gaps.yaml` by "
            "`tools/gen_known_gaps.py --write` — edit the YAML, not this "
            "section. `tests/test_docs_gaps.py` fails when this rendering "
            "drifts from the checklist, when an open gap's closer test "
            "exists and passes, or when a closed gap's closing test is "
            "missing.",
            width=72,
        ),
        "",
    ]
    for g in open_gaps:
        out.append(_wrap(f"- <!-- gap:{g['id']} -->", g["claim"]))
    out += [
        "",
        "Closed (each names the test that closes it):",
        "",
    ]
    for g in closed:
        out.append(
            _wrap(
                f"- <!-- closed-gap:{g['id']} -->",
                f"{g['claim']} Closed by `{g['closer']}`.",
            )
        )
    return "\n".join(out)


def spliced_doc(section: str) -> str:
    with open(DOC_PATH) as f:
        doc = f.read()
    assert SECTION_RE.search(doc), "doc lost its Known gaps section"
    return SECTION_RE.sub(lambda _: section + "\n", doc, count=1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--write", action="store_true")
    mode.add_argument("--check", action="store_true")
    args = ap.parse_args(argv)
    section = render(load_gaps())
    if args.write:
        new = spliced_doc(section)
        with open(DOC_PATH, "w") as f:
            f.write(new)
        return 0
    if args.check:
        with open(DOC_PATH) as f:
            current = SECTION_RE.search(f.read())
        if current and current.group(0).rstrip("\n") == section:
            return 0
        print("docs/architecture.md 'Known gaps' drifted; rerun --write")
        return 1
    print(section)
    return 0


if __name__ == "__main__":
    sys.exit(main())
