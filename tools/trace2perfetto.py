"""Convert span exports and flight-recorder bundles to Perfetto.

Two input kinds, one output: the Chrome trace-event JSON format
(https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
that ui.perfetto.dev and chrome://tracing load directly.

  - OTLP-JSON span files (`utils/tracing.OtlpJsonFileExporter` output:
    one resourceSpans batch per line) — scheduler cycle/round/solve
    spans, bench warm-cycle spans (BENCH_SPANS=...), simulator runs
    (Simulator(span_path=...)). Spans become complete ("X") events,
    one track per trace id, so a whole run's rounds and their
    setup/pass1/gather/finish segments render as a timeline.

  - `.atrace` flight-recorder bundles (armada_tpu/trace): each recorded
    round becomes a slice on its pool's track (solve wall clock wide,
    laid out sequentially when the bundle carries no timestamps), with
    the per-segment solve profile as child slices and counter tracks
    for jobs considered and pass-1 loops.

Usage:
  python tools/trace2perfetto.py run.otlp.jsonl -o run.perfetto.json
  python tools/trace2perfetto.py sim.atrace bench.otlp.jsonl -o all.json
  python tools/trace2perfetto.py --check        # fixture round-trip gate

--check converts the committed tests/fixtures/sim_steady.atrace and
validates the output is well-formed trace-event JSON with one slice per
recorded round — the tier-1 guard that keeps this converter from
rotting against the .atrace codec (tests/test_trace2perfetto.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURE = os.path.join(REPO, "tests", "fixtures", "sim_steady.atrace")

# Required keys of every emitted duration event; --check and the tier-1
# test validate each event against this.
REQUIRED_X_KEYS = ("name", "ph", "ts", "dur", "pid", "tid")


def _meta(pid: int, name: str, tid: int | None = None,
          thread_name: str | None = None) -> list[dict]:
    """Metadata events naming the process/thread tracks."""
    out = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": name},
    }]
    if tid is not None:
        out.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread_name or str(tid)},
        })
    return out


def convert_otlp(path: str) -> list[dict]:
    """OTLP-JSON lines -> trace events: one complete event per span,
    tracks keyed by trace id (a submit->lease trace reads as one lane)."""
    events: list[dict] = []
    tids: dict[str, int] = {}
    service = "spans"
    with open(path) as f:
        for ln, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                batch = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{ln + 1}: not OTLP-JSON: {e}") from e
            for resource in batch.get("resourceSpans", ()):
                for attr in resource.get("resource", {}).get("attributes", ()):
                    if attr.get("key") == "service.name":
                        service = attr["value"].get("stringValue", service)
                for scope in resource.get("scopeSpans", ()):
                    for span in scope.get("spans", ()):
                        trace_id = span.get("traceId", "")
                        tid = tids.setdefault(trace_id, len(tids) + 1)
                        start = int(span["startTimeUnixNano"])
                        end = int(span["endTimeUnixNano"])
                        events.append({
                            "name": span.get("name", "span"),
                            "cat": "span",
                            "ph": "X",
                            "ts": start / 1e3,  # trace-event time is µs
                            "dur": max(end - start, 0) / 1e3,
                            "pid": 1,
                            "tid": tid,
                            "args": {
                                a["key"]: a["value"].get("stringValue", "")
                                for a in span.get("attributes", ())
                            } | {"trace_id": trace_id,
                                 "span_id": span.get("spanId", "")},
                        })
    meta = _meta(1, f"{service} (OTLP spans)")
    for trace_id, tid in tids.items():
        meta += _meta(1, service, tid, f"trace {trace_id[:8]}")
    return meta + events


def convert_atrace(path: str) -> list[dict]:
    """Flight-recorder bundle -> trace events: one slice per recorded
    round on its pool's track. Rounds carry durations (solve_s) but not
    always wall-clock instants, so slices lay out sequentially per pool
    — the timeline shows relative cost, which is what the bundle
    records."""
    from armada_tpu.trace import load_trace

    trace = load_trace(path)
    source = trace.header.get("source", "atrace")
    pool_tids: dict[str, int] = {}
    events: list[dict] = []
    cursor_us: dict[str, float] = {}
    for r in trace.rounds:
        raw = r.raw
        pool = r.pool or "default"
        tid = pool_tids.setdefault(pool, len(pool_tids) + 1)
        solve_s = float(raw.get("solve_s") or 0.0) or 1e-3
        now = raw.get("now")
        ts_us = (
            float(now) * 1e6 if now is not None
            else cursor_us.get(pool, 0.0)
        )
        dur_us = solve_s * 1e6
        cursor_us[pool] = ts_us + dur_us
        solver = raw.get("solver") or {}
        events.append({
            "name": f"round[{raw.get('i', 0)}]",
            "cat": "round",
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": 2,
            "tid": tid,
            "args": {
                "pool": pool,
                "cycle": raw.get("cycle"),
                "num_jobs": r.num_jobs,
                "num_queues": r.num_queues,
                "backend": solver.get("backend", ""),
                "truncated": r.truncated,
            },
        })
        profile = raw.get("profile") or {}
        seg_ts = ts_us
        for seg in ("setup", "pass1", "gather", "finish"):
            seg_dur = float(profile.get(f"{seg}_s", 0.0)) * 1e6
            if seg_dur <= 0:
                continue
            events.append({
                "name": f"solve.{seg}",
                "cat": "solve",
                "ph": "X",
                "ts": seg_ts,
                "dur": seg_dur,
                "pid": 2,
                "tid": tid,
                "args": {"pool": pool},
            })
            seg_ts += seg_dur
        loops = None
        if profile:
            loops = sum(
                int(profile.get(f"{kind}_loops", 0))
                for kind in ("gang", "fill", "merged_fill")
            )
        for counter, value in (
            ("jobs considered", r.num_jobs),
            ("pass-1 loops", loops),
        ):
            if value is None:
                continue
            events.append({
                "name": counter,
                "ph": "C",
                "ts": ts_us,
                "pid": 2,
                "tid": tid,
                "args": {pool: value},
            })
    meta = _meta(2, f"flight recorder ({source})")
    for pool, tid in pool_tids.items():
        meta += _meta(2, "rounds", tid, f"pool {pool}")
    return meta + events


def sniff_kind(path: str) -> str:
    """'otlp' or 'atrace', from the first non-empty line."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}: not a JSON-lines file: {e}") from e
            if isinstance(doc, dict) and "resourceSpans" in doc:
                return "otlp"
            return "atrace"
    raise ValueError(f"{path}: empty file")


def convert(paths: list[str]) -> dict:
    events: list[dict] = []
    for path in paths:
        kind = sniff_kind(path)
        events += convert_otlp(path) if kind == "otlp" else convert_atrace(path)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate(doc: dict) -> list[str]:
    """Structural validation of the produced trace-event JSON; returns
    problems (empty = loadable)."""
    problems = []
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["no traceEvents"]
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph not in ("X", "M", "C"):
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        if ph == "X":
            missing = [k for k in REQUIRED_X_KEYS if k not in e]
            if missing:
                problems.append(f"event {i}: missing {missing}")
            elif e["dur"] < 0 or e["ts"] < 0:
                problems.append(f"event {i}: negative time")
    return problems


def check(fixture: str = FIXTURE) -> int:
    """Round-trip the committed fixture bundle; exit 0 only when the
    output is well-formed and covers every recorded round."""
    from armada_tpu.trace import load_trace

    doc = convert([fixture])
    problems = validate(doc)
    rounds = len(load_trace(fixture).rounds)
    slices = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("cat") == "round"
    ]
    if len(slices) != rounds:
        problems.append(
            f"{len(slices)} round slices for {rounds} recorded rounds"
        )
    # The JSON must survive an encode/decode round trip (what Perfetto's
    # loader does with the file).
    json.loads(json.dumps(doc))
    if problems:
        for p in problems:
            print(f"check: {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {os.path.basename(fixture)} -> {len(doc['traceEvents'])} "
        f"events covering {rounds} rounds"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("inputs", nargs="*",
                    help="OTLP-JSON span files and/or .atrace bundles")
    ap.add_argument("-o", "--output", default="",
                    help="output path (default: stdout)")
    ap.add_argument("--check", action="store_true",
                    help="round-trip the committed fixture bundle and "
                    "validate the output; exit 1 on problems")
    args = ap.parse_args(argv)
    if args.check:
        return check(args.inputs[0] if args.inputs else FIXTURE)
    if not args.inputs:
        ap.error("no inputs (or pass --check)")
    doc = convert(args.inputs)
    problems = validate(doc)
    if problems:
        for p in problems:
            print(f"invalid output: {p}", file=sys.stderr)
        return 1
    payload = json.dumps(doc)
    if args.output:
        with open(args.output, "w") as f:
            f.write(payload)
        print(
            f"wrote {len(doc['traceEvents'])} events to {args.output} "
            "(load at ui.perfetto.dev)"
        )
    else:
        print(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
