"""SLO gate: evaluate a recorded run against declared latency SLOs.

The bench gate catches throughput regressions and the replay gate
decision drift; nothing gated on what a USER feels. This tool replays
a run's latency observations through the SLO tracker
(armada_tpu/services/slo.py) and exits non-zero when any declared
objective is breached — so CI and the soaks gate on user-visible
latency, not only bit-exactness.

Inputs (repeatable, mixed):

  - `.atrace` flight-recorder bundles: every recorded round becomes a
    `round_seconds` observation (its recorded solve_s, timestamped by
    the round's virtual `now` when present);
  - bench artifacts (BENCH_r*.json driver docs or raw bench stdout
    lines): the warm-cycle samples become `round_seconds`
    observations;
  - observation documents: {"observations": [{"signal", "value",
    "now"}]} — what tools/frontdoor_soak.py and tools/chaos_soak.py
    emit under --slo.

SLO declarations come from --config (a scheduling YAML with an `slos:`
block), defaulting to services/slo.DEFAULT_SLOS; `--override
NAME=THRESHOLD[:OBJECTIVE]` tightens one in place (the "perturbed
run" proof that the gate trips — acceptance:
`python tools/slo_gate.py tests/fixtures/sim_steady.atrace` passes,
`--override round-latency=1e-6` on the same fixture exits 1).

Exit codes: 0 = every SLO met, 1 = breach, 2 = unusable (no
observations decoded / unknown override / unreadable input).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def observations_from_atrace(path: str) -> list[tuple[str, float, float]]:
    """(signal, value, now) per recorded round. Rounds without a
    virtual `now` (bench-recorded bundles) index sequentially so burn
    windows still have an ordering."""
    from armada_tpu.trace import load_trace

    trace = load_trace(path)
    out = []
    for i, rec in enumerate(trace.rounds):
        profile = rec.raw.get("profile") or {}
        solve_s = rec.raw.get("solve_s")
        if solve_s is None:
            # Older bundles: fall back to the profile's segment sum.
            solve_s = sum(
                float(profile.get(f"{seg}_s", 0.0))
                for seg in ("setup", "pass1", "gather", "finish")
            ) or None
        if solve_s is None:
            continue
        # Rounds recorded with compile telemetry (the observatory
        # header): gate the WARM cost — one-time JIT compile inside a
        # recorded solve is not the round latency users feel at steady
        # state (and the gate would otherwise fail every bundle whose
        # first round paid a cold compile).
        compiles = profile.get("compiles") or {}
        solve_s = max(
            0.0, float(solve_s) - float(compiles.get("compile_seconds", 0.0))
        )
        now = rec.raw.get("now")
        out.append(
            ("round_seconds", float(solve_s),
             float(now) if now is not None else float(i))
        )
    return out


def observations_from_doc(doc: dict) -> list[tuple[str, float, float]]:
    """Observations out of a JSON document: an explicit observations
    list, or a bench artifact's warm-cycle samples."""
    out = []
    if isinstance(doc.get("observations"), list):
        for i, o in enumerate(doc["observations"]):
            try:
                out.append(
                    (str(o["signal"]), float(o["value"]),
                     float(o.get("now", i)))
                )
            except (KeyError, TypeError, ValueError):
                continue
        return out
    # Bench artifact (either schema — reuse the bench gate's parser).
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench_gate import parse_artifact

    result = parse_artifact(doc)
    if not isinstance(result, dict):
        return out
    extra = result.get("extra") or {}
    samples = extra.get("cycle_s_samples") or []
    if not samples and isinstance(result.get("value"), (int, float)):
        samples = [result["value"]]
    for i, s in enumerate(samples):
        if isinstance(s, (int, float)):
            out.append(("round_seconds", float(s), float(i)))
    return out


def apply_overrides(slos, overrides: list[str]):
    """NAME=THRESHOLD[:OBJECTIVE] replacements; raises ValueError on an
    unknown name (a typo must not silently gate nothing)."""
    import dataclasses

    by_name = {s.name: s for s in slos}
    for spec in overrides:
        name, _, rest = spec.partition("=")
        if name not in by_name:
            raise ValueError(
                f"--override {spec!r}: no declared SLO named {name!r} "
                f"(have {sorted(by_name)})"
            )
        threshold, _, objective = rest.partition(":")
        changes = {"threshold_s": float(threshold)}
        if objective:
            changes["objective"] = float(objective)
        by_name[name] = dataclasses.replace(by_name[name], **changes)
    return tuple(by_name.values())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("inputs", nargs="+",
                    help=".atrace bundles, bench artifacts, or "
                    "observation JSON documents")
    ap.add_argument("--config", default=None,
                    help="scheduling YAML declaring an slos: block "
                    "(default: the built-in DEFAULT_SLOS)")
    ap.add_argument("--override", action="append", default=[],
                    metavar="NAME=THRESHOLD[:OBJECTIVE]",
                    help="tighten/replace one declared SLO in place "
                    "(repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON line")
    args = ap.parse_args(argv)

    from armada_tpu.services.slo import DEFAULT_SLOS, SLOTracker

    slos = DEFAULT_SLOS
    if args.config:
        from armada_tpu.core.config import load_config

        slos = load_config(args.config).slos or DEFAULT_SLOS
    try:
        slos = apply_overrides(slos, args.override)
    except ValueError as e:
        print(f"slo_gate: {e}")
        return 2

    observations: list[tuple[str, float, float]] = []
    for path in args.inputs:
        try:
            if path.endswith(".atrace"):
                observations += observations_from_atrace(path)
            else:
                with open(path) as f:
                    observations += observations_from_doc(json.load(f))
        except Exception as e:  # noqa: BLE001 - unusable input is exit 2
            print(f"slo_gate: cannot read {path}: {e}")
            return 2
    if not observations:
        print("slo_gate: no SLO observations decoded from the inputs")
        return 2

    tracker = SLOTracker(slos)
    # Burn windows need time order however many inputs were mixed.
    observations.sort(key=lambda o: o[2])
    for signal, value, now in observations:
        tracker.observe(signal, value, now=now)
    report = tracker.evaluate(now=observations[-1][2])
    report["observations"] = len(observations)
    if args.json:
        print(json.dumps(report))
    else:
        for s in report["slos"]:
            if not s["observed"]:
                continue
            print(
                f"{s['name']}: {s['good']}/{s['observed']} good "
                f"(compliance {s['compliance']:.4f} vs objective "
                f"{s['objective']}) on {s['signal']} <= "
                f"{s['threshold_s']}s"
            )
        for line in report["breaches"]:
            print("BREACH " + line)
        verdict = "OK" if report["ok"] else "BREACHED"
        print(
            f"slo_gate: {len(observations)} observation(s) across "
            f"{len(args.inputs)} input(s): {verdict}"
        )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
