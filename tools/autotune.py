"""Offline solver-parameter tuner over a flight-recorder corpus.

The tooling analogue of tools/replay_gate.py, but instead of gating one
kernel it SEARCHES: every candidate parameter vector (hot-window slots,
engagement floor, budgeted chunk stride) re-solves every recorded round
and must reproduce the recorded decision stream bit-for-bit; qualifying
candidates are timed warm over the whole corpus and the fastest one is
emitted as a tuned profile the scheduler loads at boot
(`autotuneProfile` in the scheduling config, or merged into the
persisted tuning store).

    # tiny smoke grid over the committed fixture corpus
    python tools/autotune.py tests/fixtures/sim_steady.atrace \
        --windows 2,4 --min-slots 0 --allow-foreign --out tuned.json

    # production search: record a corpus first (BENCH_TRACE=..., or
    # Simulator(trace_path=...), or scheduler.attach_trace_recorder)
    python tools/autotune.py burst.atrace --repeats 5 --out tuned.json

A bundle recorded on a different target refuses to tune (parameters
timed under different arithmetic/toolchain say nothing about this
host); pass --allow-foreign only for x64-recorded bundles, whose exact
decisions are host-independent — the TIMINGS still describe this host,
which is the point. Exit codes: 0 profile written/printed, 1 any
candidate diverged (a solver bug, not a tuning outcome), 2 unusable
corpus (no rounds, undecodable bundle, target mismatch).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _int_list(raw: str) -> list[int]:
    return [int(tok) for tok in raw.split(",") if tok.strip() != ""]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("traces", nargs="+", help=".atrace bundles to tune over")
    ap.add_argument("--windows", default=None,
                    help="comma-separated hot-window sizes to try "
                    "(default: the pow2 buckets around the shipped 4096)")
    ap.add_argument("--min-slots", default=None,
                    help="comma-separated engagement floors to try "
                    "(default: the shipped hotWindowMinSlots floor)")
    ap.add_argument("--chunks", default="1",
                    help="comma-separated budgeted chunk strides to try")
    ap.add_argument("--max-rounds", type=int, default=0,
                    help="tune over at most N rounds (0 = all)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="warm timing repetitions per candidate (median)")
    ap.add_argument("--pool", default=None,
                    help="pool the tuned entry applies to (default: the "
                    "corpus's single pool, else '*')")
    ap.add_argument("--allow-foreign", action="store_true",
                    help="tune a bundle recorded on a different host "
                    "(sound only for x64-recorded traces)")
    ap.add_argument("--out", default=None,
                    help="write the selected entry as a tuning-store "
                    "profile JSON here")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON line")
    args = ap.parse_args(argv)

    # Match the production solver configuration BEFORE any jax-touching
    # import (same preamble as tools/replay_gate.py).
    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_healthy_backend()

    from armada_tpu.autotune import TuningStore, default_grid, tune_corpus
    from armada_tpu.autotune.offline import DEFAULT_WINDOWS
    from armada_tpu.core.config import HOT_WINDOW_MIN_SLOTS_DEFAULT
    from armada_tpu.trace import TraceFormatError, TraceTargetMismatch, load_trace

    traces = []
    for path in args.traces:
        try:
            traces.append(load_trace(path))
        except (OSError, TraceFormatError) as e:
            print(f"autotune: cannot load {path}: {e}")
            return 2

    candidates = default_grid(
        windows=_int_list(args.windows) if args.windows else DEFAULT_WINDOWS,
        min_slots=(
            _int_list(args.min_slots)
            if args.min_slots is not None
            else (HOT_WINDOW_MIN_SLOTS_DEFAULT,)
        ),
        chunks=_int_list(args.chunks) or [1],
    )

    try:
        report = tune_corpus(
            traces,
            candidates,
            max_rounds=args.max_rounds or None,
            repeats=args.repeats,
            allow_foreign=args.allow_foreign,
            pool=args.pool,
            log=None if args.json else print,
        )
    except TraceTargetMismatch as e:
        print(f"autotune: {e}")
        return 2
    except ValueError as e:
        print(f"autotune: {e}")
        return 2

    selected = report["selected"]
    # A run with ANY diverging candidate is a solver bug (exit 1): it
    # must not mint a profile file something could later adopt.
    if args.out and selected is not None and report["ok"]:
        store = TuningStore()
        store.put(selected)
        store.to_json(args.out)
    if args.json:
        print(json.dumps(report))
    else:
        print(f"corpus: {report['rounds']} round(s), "
              f"workload {report['workload']}")
        for r in report["results"]:
            status = (
                f"{r['wall_s']:.4f}s" if r["bit_exact"]
                else f"DIVERGED x{len(r['divergences'])}"
            )
            print(f"  {r['label']:<24} {status}")
        if selected is not None:
            p = selected["params"]
            print(
                f"selected: {selected['meta']['label']} "
                f"(window={p['hot_window_slots']} "
                f"min_slots={p['hot_window_min_slots']} "
                f"chunk={p['chunk_loops']}) "
                f"baseline {report['baseline']['wall_s']}s -> "
                f"{selected['tuned_s']}s"
                + (f" -> wrote {args.out}"
                   if args.out and report["ok"] else "")
            )
    if not report["ok"]:
        # stderr: with --json the LAST stdout line must stay the
        # machine-readable report (the bench.py artifact convention).
        print(
            "autotune: candidate(s) diverged from the recorded decision "
            "stream — investigate with tools/replay_gate.py",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
