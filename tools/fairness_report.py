"""Offline fairness scorecard: Jain/regret trajectory + per-queue ledger
aggregates from flight-recorder bundles or a built-in contention sim.

    python tools/fairness_report.py trace.atrace [trace2.atrace ...]
    python tools/fairness_report.py trace.atrace --json
    python tools/fairness_report.py --sim            # canned 3-queue sim

Per round the report uses the bundle's recorded `fairness` block (the
canonical index-based ledger + preemption attribution the scheduler
stamped at solve time, observe/fairness.py); rounds from bundles
recorded before the fairness round are recomputed from their own
DeviceRound + decision stream with the same function — identical math,
so old corpora still get a scorecard. Queue indices resolve to names
through the bundle's id vocabularies when recorded.

This is the offline face of the fairness observatory: the same
scorecard the live surfaces serve (`armadactl fairness`,
`GET /api/fairness`), computable over any recorded corpus — the
substrate the pluggable-fairness A/B harness (ROADMAP item 4) will run
candidate policies through.

Exit codes: 0 ok, 2 unusable input (no rounds / undecodable bundle).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def blocks_from_trace(path: str):
    """(blocks, meta) — one decorated fairness block per non-truncated
    round (queue indices resolved to names when the bundle recorded id
    vocabularies)."""
    from armada_tpu.trace import load_trace

    from armada_tpu.observe.fairness import resolve_names

    trace = load_trace(path)
    blocks = []
    recomputed = 0
    for rec in trace.rounds:
        if rec.truncated:
            continue
        block = rec.raw.get("fairness")
        if not block:
            from armada_tpu.observe.fairness import ledger_from_device_round

            block = ledger_from_device_round(
                rec.device_round(), rec.decisions(), rec.num_jobs,
                rec.num_queues,
            )
            recomputed += 1
        ids = rec.raw.get("ids") or {}
        blocks.append(
            resolve_names(
                block,
                queue_names=ids.get("queues"),
                job_ids=ids.get("jobs"),
            )
        )
    return blocks, {
        "path": path,
        "rounds": len(blocks),
        "recomputed": recomputed,
    }


def blocks_from_sim():
    """A deterministic 3-queue starvation sim on the REAL service path:
    two equal-weight queues holding the fleet with non-preemptible
    work, plus a weight-starved victim (priority factor 20 → weight
    0.05) whose demand can never be delivered — the starvation-alert
    scenario from the "Diagnosing an unfair pool" runbook."""
    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    cfg = SchedulingConfig(
        priority_classes={
            "low": PriorityClass("low", 1000, preemptible=True),
            "pinned": PriorityClass("pinned", 30000, preemptible=False),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
    )
    long = ShiftedExponential(minimum=500.0)
    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    name="qa",
                    job_templates=(
                        JobTemplate(id="a", number=4, cpu="4",
                                    priority_class="pinned", runtime=long),
                    ),
                ),
                QueueSpecSim(
                    name="qb",
                    job_templates=(
                        JobTemplate(id="b", number=4, cpu="4",
                                    submit_time=30.0,
                                    priority_class="pinned", runtime=long),
                    ),
                ),
                QueueSpecSim(
                    name="qc",
                    priority_factor=20.0,  # weight 0.05: the victim
                    job_templates=(
                        JobTemplate(id="c", number=4, cpu="4",
                                    submit_time=60.0, runtime=long),
                    ),
                ),
            )
        ),
        config=cfg,
        backend="oracle",
        cycle_interval=10.0,
        max_time=300.0,
    )
    blocks = []
    orig = sim.scheduler.fairness.observe_round

    def tap(pool, fairness, **kw):
        doc = orig(pool, fairness, **kw)
        blocks.append(
            {"ledger": doc["ledger"], "preemptions": doc["preemptions"]}
        )
        return doc

    sim.scheduler.fairness.observe_round = tap
    sim.run()
    return blocks, {"path": "<sim>", "rounds": len(blocks), "recomputed": 0}


def render(scorecard: dict, metas: list) -> str:
    lines = []
    for meta in metas:
        extra = (
            f" ({meta['recomputed']} recomputed pre-fairness rounds)"
            if meta.get("recomputed")
            else ""
        )
        lines.append(f"{meta['path']}: {meta['rounds']} round(s){extra}")
    lines.append(
        f"jain mean {scorecard['jain_mean']:.4f} min "
        f"{scorecard['jain_min']:.4f} · max regret "
        f"{scorecard['max_regret']:.4f} over {scorecard['rounds']} rounds"
    )
    header = (
        f"{'queue':<16} {'rounds':>6} {'entitled':>9} {'delivered':>9} "
        f"{'demand':>8} {'regretΣ':>9} {'regret^':>8} {'starved':>8} "
        f"{'streak^':>8}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for name, q in scorecard["queues"].items():
        lines.append(
            f"{name:<16} {q['rounds']:>6} {q['mean_entitlement']:>9.4f} "
            f"{q['mean_delivered']:>9.4f} {q['mean_demand']:>8.4f} "
            f"{q['regret_total']:>9.4f} {q['max_regret']:>8.4f} "
            f"{q['starved_rounds']:>8} {q['max_starved_streak']:>8}"
        )
    attributed = scorecard.get("preemptions_attributed") or {}
    if attributed:
        lines.append("preemptions attributed (aggressor/mechanism):")
        for key, n in attributed.items():
            lines.append(f"  {key}: {n}")
    tail = scorecard.get("trajectory", [])[-10:]
    if tail:
        lines.append("trajectory (last 10 rounds):")
        for t in tail:
            lines.append(
                f"  round {t['round']:>4}: jain {t['jain']:.4f}  "
                f"max regret {t['max_regret']:.4f}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("traces", nargs="*", help=".atrace bundles to score")
    ap.add_argument("--sim", action="store_true",
                    help="score the built-in 3-queue contention sim "
                    "instead of bundles")
    ap.add_argument("--json", action="store_true",
                    help="emit the scorecard document as one JSON line")
    args = ap.parse_args(argv)
    if not args.traces and not args.sim:
        ap.error("give .atrace bundle(s) or --sim")

    from armada_tpu.observe.fairness import aggregate_scorecard
    from armada_tpu.trace import TraceFormatError

    blocks: list = []
    metas: list = []
    if args.sim:
        b, meta = blocks_from_sim()
        blocks += b
        metas.append(meta)
    for path in args.traces:
        try:
            b, meta = blocks_from_trace(path)
        except (OSError, TraceFormatError) as e:
            print(f"fairness_report: cannot load {path}: {e}")
            return 2
        blocks += b
        metas.append(meta)
    if not blocks:
        print("fairness_report: no scoreable rounds in the given input "
              "(all truncated or empty)")
        return 2
    scorecard = aggregate_scorecard(blocks)
    if args.json:
        print(json.dumps({"scorecard": scorecard, "inputs": metas}))
    else:
        print(render(scorecard, metas))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
