"""Chaos soak: seeded FaultPlans through the simulator, invariants asserted.

Runs N seeded fault plans (executor crashes/hangs, lease faults, leader
flaps, torn event-log writes, and network partitions on the virtual
clock) through whole-fleet simulator runs on the REAL control-plane code
path, asserting after each:

  - zero jobdb invariant violations (enable_assertions runs
    txn.assert_valid() after every cycle — including the split-brain
    invariant that no job ever holds two active runs);
  - every job reached a terminal state (faults delay work, never lose it);
  - determinism: the same seed run twice produces the IDENTICAL final
    jobdb digest (state + final placement per job) — the property that
    makes chaos failures reproducible from a one-line seed.

Every seeded plan carries partition faults on top of the generated mix:
a short sever that heals MID-LEASE (window < executor timeout, so held
work resumes and reports late), a long partition that heals only AFTER
the scheduler reassigned the executor's runs (anti-entropy must resolve
the zombies/duplicates to exactly one terminal outcome per job), and the
workload includes gang waves so partitions land during gang placement.

Usage:
  python tools/chaos_soak.py [--plans 20] [--backend oracle]
                             [--jobs 40] [--no-determinism-check]
  python tools/chaos_soak.py --solver-faults --plans 3 --jobs 24

--solver-faults switches to the self-healing-solve-path soak (kernel
backend): seeded windows of solver_raise / solver_hang /
solver_nan_poison / solver_wrong_placement over live rounds, asserting
every fault fired and was contained (no invalid round committed, all
jobs terminal), every rejection left a loadable .atrace postmortem that
replays DIVERGED offline, and the run is seed-deterministic.

Exit code 0 = clean soak; prints one JSON line per plan and a summary.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# The soak's declared SLOs (--slo): round latency is REAL wall clock
# per cycle (oracle cycles are milliseconds; a 5s cycle under this tiny
# fleet is a pathology), queue wait is VIRTUAL seconds — generous
# headroom over the worst legitimate partition-window requeue delay so
# chaos-delayed-but-recovered work does not false-positive the gate.
def soak_slos(queue_wait_s: float = 3600.0, round_s: float = 5.0):
    from armada_tpu.core.config import SLOSpec

    return (
        SLOSpec(name="round-latency", signal="round_seconds",
                threshold_s=round_s, objective=0.95),
        SLOSpec(name="queue-wait", signal="queue_wait_seconds",
                threshold_s=queue_wait_s, objective=0.95),
    )


def build_sim(seed: int, backend: str, n_jobs: int, data_dir: str | None,
              slos=None):
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.chaos import FaultPlan, FaultSpec
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    executors = ["chaos-c0", "chaos-c1"]
    # The workload spans the same horizon the fault windows are drawn
    # over (waves of submissions through [0, 0.75*duration)), so crash /
    # flap / torn-write windows actually intersect live work.
    duration = 1200.0
    timeout = 120.0
    generated = FaultPlan.generate(
        seed, duration, executors=executors, events_per_kind=2
    )
    # Partition faults on top of the generated mix, engineered around the
    # executor timeout so every seed exercises both heal regimes:
    #   - short sever healing MID-LEASE (window < timeout: no expiry,
    #     held pods report late);
    #   - long partition healing AFTER REASSIGNMENT (window > timeout:
    #     runs expired + fence bumped while dark; anti-entropy must
    #     resolve zombies/duplicates to one terminal outcome per job).
    # Starts anchor just after a submission wave lands (waves at
    # 0/225/450/675; placements need a cycle, runtimes are >= 60s), so
    # the sever catches pods RUNNING on the target — with small per-seed
    # jitter so the interleaving still varies. The long partition also
    # overlaps the second gang wave (t=490).
    wave = duration * 0.75 / 4
    short_start = wave + 35.0 + (seed % 4) * 5.0
    long_start = 2 * wave + 30.0 + (seed % 4) * 5.0
    partitions = (
        FaultSpec(
            "network_partition",
            executors[seed % 2],
            start=short_start,
            duration=timeout * 0.5,
        ),
        FaultSpec(
            "network_partition",
            executors[(seed + 1) % 2],
            start=long_start,
            duration=timeout * 2.0,
        ),
        # Second long sever on the OTHER link, over the last wave: both
        # executors see a heal-after-reassignment partition every seed,
        # whatever the generated crash/hang windows blot out.
        FaultSpec(
            "network_partition",
            executors[seed % 2],
            start=3 * wave + 30.0 + (seed % 4) * 5.0,
            duration=timeout * 2.0,
        ),
    )
    plan = FaultPlan(
        sorted(
            generated.faults + partitions,
            key=lambda f: (f.start, f.kind, f.target),
        ),
        seed=seed,
    )
    config = SchedulingConfig(
        enable_assertions=True,  # jobdb invariants checked every cycle
        # Crashed executors must expire well inside the sim horizon.
        executor_timeout_s=timeout,
        max_retries=10,
    )
    clusters = [
        ClusterSpec(name=name, node_templates=(NodeTemplate(count=10),))
        for name in executors
    ]
    waves = 4
    per_wave = max(1, n_jobs // (2 * waves))
    workload = WorkloadSpec(
        queues=tuple(
            QueueSpecSim(
                name=f"q{i}",
                job_templates=tuple(
                    JobTemplate(
                        id=f"t{i}w{w}",
                        number=per_wave,
                        cpu="2",
                        memory="4Gi",
                        runtime=ShiftedExponential(minimum=60.0, tail_mean=60.0),
                        submit_time=w * duration * 0.75 / waves + i * 20.0,
                    )
                    for w in range(waves)
                )
                # Gang waves: all-or-nothing placements in flight while
                # partitions sever an executor (the gang path is where a
                # half-resurrected zombie would hurt most).
                + tuple(
                    JobTemplate(
                        id=f"g{i}w{w}",
                        number=4,
                        gang_cardinality=2,
                        cpu="2",
                        memory="4Gi",
                        runtime=ShiftedExponential(minimum=90.0),
                        submit_time=(
                            w * duration * 0.75 / waves + 40.0 + i * 20.0
                        ),
                    )
                    for w in range(0, waves, 2)
                ),
            )
            for i in range(2)
        )
    )
    slo_tracker = None
    if slos:
        from armada_tpu.services.slo import SLOTracker

        slo_tracker = SLOTracker(slos)
    return Simulator(
        clusters,
        workload,
        config,
        backend=backend,
        seed=seed,
        cycle_interval=10.0,
        max_time=6 * 3600.0,
        fault_plan=plan,
        data_dir=data_dir,
        slo=slo_tracker,
    ), plan


def jobdb_digest(sim) -> str:
    """Stable digest of final per-job state + placement (run ids excluded:
    they are fresh uuids every run by design)."""
    txn = sim.scheduler.jobdb.read_txn()
    rows = []
    for job in sorted(txn.all_jobs(), key=lambda j: j.id):
        run = job.latest_run
        rows.append(
            (
                job.id,
                job.state.value,
                job.num_attempts,
                run.node_id if run is not None else "",
            )
        )
    return hashlib.sha256(json.dumps(rows).encode()).hexdigest()


def run_plan(seed: int, backend: str = "oracle", n_jobs: int = 40,
             use_file_log: bool = True, slos=None) -> dict:
    """One soak iteration; raises on any invariant violation (with
    `slos`, a declared-SLO breach — services/slo.py burn-rate verdict
    over the run — is an invariant violation too)."""
    tmp = None
    data_dir = None
    if use_file_log:
        tmp = tempfile.TemporaryDirectory(prefix=f"chaos-soak-{seed}-")
        data_dir = tmp.name
    try:
        sim, plan = build_sim(seed, backend, n_jobs, data_dir, slos=slos)
        result = sim.run()
        # Final invariant sweep on top of the per-cycle assertions
        # (assert_valid includes the split-brain invariant: at most one
        # live run per job, every run id owned by exactly one job).
        txn = sim.scheduler.jobdb.read_txn()
        txn.assert_valid()
        # Explicit double-active-run sweep, belt over the braces: a
        # healed partition must never leave a job running twice.
        from armada_tpu.jobdb.jobdb import RunState

        live = (RunState.LEASED, RunState.PENDING, RunState.RUNNING)
        for job in txn.all_jobs():
            active = [r.id for r in job.runs if r.state in live]
            if len(active) > 1:
                raise AssertionError(
                    f"seed {seed}: job {job.id} holds two active runs "
                    f"{active} after the soak"
                )
        unfinished = result.total_jobs - sum(
            1 for s in result.events_by_job.values() if s.terminal
        )
        if unfinished:
            raise AssertionError(
                f"seed {seed}: {unfinished}/{result.total_jobs} jobs never "
                "reached a terminal state under chaos"
            )
        slo_verdict = None
        if sim.slo is not None:
            slo_verdict = sim.slo.evaluate(now=result.makespan)
            if not slo_verdict["ok"]:
                raise AssertionError(
                    f"seed {seed}: SLO breach: "
                    + "; ".join(slo_verdict["breaches"])
                )
        crashes = getattr(sim.log, "crashes", 0)
        anti_entropy: dict = {}
        for ex in sim.executors:
            for kind, count in getattr(ex, "anti_entropy", {}).items():
                anti_entropy[kind] = anti_entropy.get(kind, 0) + count
        return {
            "seed": seed,
            "digest": jobdb_digest(sim),
            "finished": result.finished_jobs,
            "total": result.total_jobs,
            "preemptions": result.preemptions,
            "cycles": result.cycles,
            "makespan": round(result.makespan, 1),
            "faults_fired": plan.fired(),
            "log_crashes": crashes,
            "anti_entropy": anti_entropy,
            "fences": dict(sim.scheduler.executor_fences),
            **(
                {
                    "slo": {
                        "ok": slo_verdict["ok"],
                        "slos": [
                            {k: s[k] for k in ("name", "observed", "good",
                                               "bad", "compliance")}
                            for s in slo_verdict["slos"]
                        ],
                    }
                }
                if slo_verdict is not None
                else {}
            ),
        }
    finally:
        if tmp is not None:
            tmp.cleanup()


# ------------------------------------------------- solver-fault soak mode

def build_solver_sim(seed: int, n_jobs: int, data_dir: str):
    """Kernel-backend sim under a solver-fault plan: each fault kind the
    self-healing solve path contains (services/chaos.SOLVER_FAULT_KINDS)
    gets its own window over cycles where backlogged work guarantees a
    live solve. One small cluster, multi-wave backlog (jobs >> cores, 60s+
    runtimes) so rounds keep solving through every window:

      - solver_hang over the first wave's backlog: the primary rung
        fails over same-cycle;
      - solver_raise with count=9 on "*": every rung raises for 3
        consecutive rounds, opening the non-terminal circuit breakers
        (threshold 3) — rounds land on the oracle terminal rung (always
        offered, open breaker or not) until the cooldown's shadow probe
        restores the ladder;
      - solver_nan_poison / solver_wrong_placement on later waves: the
        admission firewall rejects the poisoned round on each corrupted
        rung (nothing commits, work requeues) and quarantines a
        single-round .atrace postmortem under data_dir/quarantine.
    """
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.chaos import FaultPlan, FaultSpec
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    j = (seed % 4) * 5.0  # per-seed window jitter: vary the hit rounds
    faults = (
        FaultSpec("solver_hang", "*", start=12.0 + j, duration=40.0,
                  count=1),
        FaultSpec("solver_raise", "*", start=102.0 + j, duration=40.0,
                  count=9),
        FaultSpec("solver_nan_poison", "*", start=202.0 + j, duration=40.0,
                  count=2),
        FaultSpec("solver_wrong_placement", "*", start=302.0 + j,
                  duration=40.0, count=2),
    )
    plan = FaultPlan(faults, seed=seed)
    config = SchedulingConfig(
        enable_assertions=True,
        solver_validate=True,
        solver_failover=True,
        max_retries=10,
    )
    clusters = [
        ClusterSpec(
            name="solver-c0",
            node_templates=(NodeTemplate(count=1, cpu="8", memory="64Gi"),),
        )
    ]
    waves = 4
    per_wave = max(2, n_jobs // waves)
    workload = WorkloadSpec(
        queues=(
            QueueSpecSim(
                name="q0",
                job_templates=tuple(
                    JobTemplate(
                        id=f"sw{w}",
                        number=per_wave,
                        cpu="2",
                        memory="4Gi",
                        runtime=ShiftedExponential(minimum=60.0,
                                                   tail_mean=30.0),
                        submit_time=w * 100.0,
                    )
                    for w in range(waves)
                ),
            ),
        )
    )
    return Simulator(
        clusters,
        workload,
        config,
        backend="kernel",
        seed=seed,
        cycle_interval=10.0,
        max_time=2 * 3600.0,
        fault_plan=plan,
        data_dir=data_dir,
    ), plan


def run_solver_plan(seed: int, n_jobs: int = 24, replay: bool = True) -> dict:
    """One solver-fault soak iteration; raises when containment failed:
    a planned fault kind never fired, an invariant violation committed
    (jobdb assert_valid / double-active-run sweep), a job never reached
    a terminal state, a rejection has no loadable postmortem bundle, or
    (with replay=True) a quarantined round replays CLEAN under a healthy
    solver — the bundle must reproduce the corruption offline as a
    placement divergence."""
    from armada_tpu.services.chaos import SOLVER_FAULT_KINDS

    with tempfile.TemporaryDirectory(
        prefix=f"chaos-solver-{seed}-"
    ) as data_dir:
        sim, plan = build_solver_sim(seed, n_jobs, data_dir)
        result = sim.run()
        txn = sim.scheduler.jobdb.read_txn()
        txn.assert_valid()
        from armada_tpu.jobdb.jobdb import RunState

        live = (RunState.LEASED, RunState.PENDING, RunState.RUNNING)
        for job in txn.all_jobs():
            active = [r.id for r in job.runs if r.state in live]
            if len(active) > 1:
                raise AssertionError(
                    f"seed {seed}: job {job.id} holds two active runs "
                    f"{active} after the solver-fault soak"
                )
        unfinished = result.total_jobs - sum(
            1 for s in result.events_by_job.values() if s.terminal
        )
        if unfinished:
            raise AssertionError(
                f"seed {seed}: {unfinished}/{result.total_jobs} jobs never "
                "reached a terminal state under solver faults"
            )
        chaos = sim.scheduler.solver_chaos
        injected = dict(chaos.injected) if chaos is not None else {}
        for kind in SOLVER_FAULT_KINDS:
            if not injected.get(kind):
                raise AssertionError(
                    f"seed {seed}: planned fault {kind} never fired "
                    f"(injected={injected}) — the plan windows missed "
                    "every live solve"
                )
        rejections = list(sim.scheduler.recent_rejections)
        if not rejections:
            raise AssertionError(
                f"seed {seed}: corruption faults fired but the admission "
                "firewall rejected nothing"
            )
        failovers = list(sim.scheduler.recent_failovers)
        if not failovers:
            raise AssertionError(
                f"seed {seed}: solver faults fired but no failover was "
                "recorded"
            )
        replayed = 0
        for rej in rejections:
            bundle = rej.get("bundle")
            if not bundle or not os.path.exists(bundle):
                raise AssertionError(
                    f"seed {seed}: rejection {rej['invariant']} on "
                    f"{rej['rung']} (cycle {rej['cycle']}) has no "
                    f"postmortem bundle at {bundle!r}"
                )
            from armada_tpu.trace import load_trace, replay_trace

            trace = load_trace(bundle)
            if not replay:
                continue
            # The quarantined round must reproduce its corruption
            # offline: a healthy LOCAL replay of the recorded (poisoned)
            # decisions diverges on placement. The recording process IS
            # this process, so no target/x64 mismatch arises.
            report = replay_trace(
                trace, solvers=["LOCAL"], log=lambda msg: None
            )
            if not report["divergences"].get("placement"):
                raise AssertionError(
                    f"seed {seed}: quarantined round {os.path.basename(bundle)} "
                    "replayed CLEAN — the bundle does not reproduce the "
                    f"corruption (divergences={report['divergences']})"
                )
            replayed += 1
        ladder = (
            sim.scheduler.doctor_report().get("ladder")
            if hasattr(sim.scheduler, "doctor_report")
            else None
        )
        return {
            "seed": seed,
            "mode": "solver-faults",
            "digest": jobdb_digest(sim),
            "finished": result.finished_jobs,
            "total": result.total_jobs,
            "cycles": result.cycles,
            "makespan": round(result.makespan, 1),
            "injected": injected,
            "rejections": [
                {k: rej[k] for k in ("cycle", "rung", "invariant")}
                for rej in rejections
            ],
            "failovers": [
                {k: fo[k] for k in ("cycle", "from", "to", "cause")}
                for fo in failovers
            ],
            "bundles_replayed": replayed,
            "ladder": ladder,
        }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos-soak")
    ap.add_argument("--plans", type=int, default=20)
    ap.add_argument("--backend", default="oracle",
                    choices=["oracle", "kernel"])
    ap.add_argument("--jobs", type=int, default=40)
    ap.add_argument("--no-determinism-check", action="store_true")
    ap.add_argument("--solver-faults", action="store_true",
                    help="run the solver-fault soak instead (kernel "
                    "backend, solver_raise/hang/nan_poison/"
                    "wrong_placement windows; asserts containment, "
                    "quarantine bundles, and offline replay — use with "
                    "--plans 3 and --jobs 24)")
    ap.add_argument("--slo", action="store_true",
                    help="gate each plan on the soak's declared SLOs "
                    "(services/slo.py): real-wall round latency and "
                    "virtual-clock queue wait")
    ap.add_argument("--slo-queue-wait", type=float, default=3600.0,
                    help="queue-wait SLO threshold in VIRTUAL seconds "
                    "(with --slo; a deliberately tiny value proves the "
                    "gate trips)")
    args = ap.parse_args(argv)

    slos = (
        soak_slos(queue_wait_s=args.slo_queue_wait) if args.slo else None
    )
    failures = 0
    for seed in range(args.plans):
        try:
            if args.solver_faults:
                first = run_solver_plan(seed, args.jobs)
                if not args.no_determinism_check:
                    # Replay already proved the bundles diverge on the
                    # first run; the determinism pass only needs digests.
                    second = run_solver_plan(seed, args.jobs, replay=False)
                    if first["digest"] != second["digest"]:
                        raise AssertionError(
                            f"seed {seed}: nondeterministic final jobdb "
                            f"({first['digest'][:12]} != "
                            f"{second['digest'][:12]})"
                        )
                print(json.dumps(first))
                continue
            first = run_plan(seed, args.backend, args.jobs, slos=slos)
            if not args.no_determinism_check:
                second = run_plan(seed, args.backend, args.jobs, slos=slos)
                if first["digest"] != second["digest"]:
                    raise AssertionError(
                        f"seed {seed}: nondeterministic final jobdb "
                        f"({first['digest'][:12]} != {second['digest'][:12]})"
                    )
            print(json.dumps(first))
        except Exception as e:
            failures += 1
            print(json.dumps({"seed": seed, "error": repr(e)}))
    print(
        json.dumps(
            {
                "plans": args.plans,
                "failures": failures,
                "determinism_checked": not args.no_determinism_check,
            }
        )
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
