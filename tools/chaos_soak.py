"""Chaos soak: seeded FaultPlans through the simulator, invariants asserted.

Runs N seeded fault plans (executor crashes/hangs, lease faults, leader
flaps, torn event-log writes) through whole-fleet simulator runs on the
REAL control-plane code path, asserting after each:

  - zero jobdb invariant violations (enable_assertions runs
    txn.assert_valid() after every cycle);
  - every job reached a terminal state (faults delay work, never lose it);
  - determinism: the same seed run twice produces the IDENTICAL final
    jobdb digest (state + final placement per job) — the property that
    makes chaos failures reproducible from a one-line seed.

Usage:
  python tools/chaos_soak.py [--plans 20] [--backend oracle]
                             [--jobs 40] [--no-determinism-check]

Exit code 0 = clean soak; prints one JSON line per plan and a summary.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_sim(seed: int, backend: str, n_jobs: int, data_dir: str | None):
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.chaos import FaultPlan
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    executors = ["chaos-c0", "chaos-c1"]
    # The workload spans the same horizon the fault windows are drawn
    # over (waves of submissions through [0, 0.75*duration)), so crash /
    # flap / torn-write windows actually intersect live work.
    duration = 1200.0
    plan = FaultPlan.generate(
        seed, duration, executors=executors, events_per_kind=2
    )
    config = SchedulingConfig(
        enable_assertions=True,  # jobdb invariants checked every cycle
        # Crashed executors must expire well inside the sim horizon.
        executor_timeout_s=120.0,
        max_retries=10,
    )
    clusters = [
        ClusterSpec(name=name, node_templates=(NodeTemplate(count=10),))
        for name in executors
    ]
    waves = 4
    per_wave = max(1, n_jobs // (2 * waves))
    workload = WorkloadSpec(
        queues=tuple(
            QueueSpecSim(
                name=f"q{i}",
                job_templates=tuple(
                    JobTemplate(
                        id=f"t{i}w{w}",
                        number=per_wave,
                        cpu="2",
                        memory="4Gi",
                        runtime=ShiftedExponential(minimum=60.0, tail_mean=60.0),
                        submit_time=w * duration * 0.75 / waves + i * 20.0,
                    )
                    for w in range(waves)
                ),
            )
            for i in range(2)
        )
    )
    return Simulator(
        clusters,
        workload,
        config,
        backend=backend,
        seed=seed,
        cycle_interval=10.0,
        max_time=6 * 3600.0,
        fault_plan=plan,
        data_dir=data_dir,
    ), plan


def jobdb_digest(sim) -> str:
    """Stable digest of final per-job state + placement (run ids excluded:
    they are fresh uuids every run by design)."""
    txn = sim.scheduler.jobdb.read_txn()
    rows = []
    for job in sorted(txn.all_jobs(), key=lambda j: j.id):
        run = job.latest_run
        rows.append(
            (
                job.id,
                job.state.value,
                job.num_attempts,
                run.node_id if run is not None else "",
            )
        )
    return hashlib.sha256(json.dumps(rows).encode()).hexdigest()


def run_plan(seed: int, backend: str = "oracle", n_jobs: int = 40,
             use_file_log: bool = True) -> dict:
    """One soak iteration; raises on any invariant violation."""
    tmp = None
    data_dir = None
    if use_file_log:
        tmp = tempfile.TemporaryDirectory(prefix=f"chaos-soak-{seed}-")
        data_dir = tmp.name
    try:
        sim, plan = build_sim(seed, backend, n_jobs, data_dir)
        result = sim.run()
        # Final invariant sweep on top of the per-cycle assertions.
        sim.scheduler.jobdb.read_txn().assert_valid()
        unfinished = result.total_jobs - sum(
            1 for s in result.events_by_job.values() if s.terminal
        )
        if unfinished:
            raise AssertionError(
                f"seed {seed}: {unfinished}/{result.total_jobs} jobs never "
                "reached a terminal state under chaos"
            )
        crashes = getattr(sim.log, "crashes", 0)
        return {
            "seed": seed,
            "digest": jobdb_digest(sim),
            "finished": result.finished_jobs,
            "total": result.total_jobs,
            "preemptions": result.preemptions,
            "cycles": result.cycles,
            "makespan": round(result.makespan, 1),
            "faults_fired": plan.fired(),
            "log_crashes": crashes,
        }
    finally:
        if tmp is not None:
            tmp.cleanup()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos-soak")
    ap.add_argument("--plans", type=int, default=20)
    ap.add_argument("--backend", default="oracle",
                    choices=["oracle", "kernel"])
    ap.add_argument("--jobs", type=int, default=40)
    ap.add_argument("--no-determinism-check", action="store_true")
    args = ap.parse_args(argv)

    failures = 0
    for seed in range(args.plans):
        try:
            first = run_plan(seed, args.backend, args.jobs)
            if not args.no_determinism_check:
                second = run_plan(seed, args.backend, args.jobs)
                if first["digest"] != second["digest"]:
                    raise AssertionError(
                        f"seed {seed}: nondeterministic final jobdb "
                        f"({first['digest'][:12]} != {second['digest'][:12]})"
                    )
            print(json.dumps(first))
        except Exception as e:
            failures += 1
            print(json.dumps({"seed": seed, "error": repr(e)}))
    print(
        json.dumps(
            {
                "plans": args.plans,
                "failures": failures,
                "determinism_checked": not args.no_determinism_check,
            }
        )
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
