"""Fairness-policy A/B: replay a corpus under candidate policies.

    python tools/policy_ab.py trace.atrace
    python tools/policy_ab.py trace.atrace --policy drf --policy priority
    python tools/policy_ab.py trace.atrace --json --rounds 20

Every non-truncated round in the bundle(s) is re-solved under each
candidate fairness policy (solver/policy.py) — the spec is swapped into
the recorded DeviceRound's static meta, so each candidate sees the
exact round inputs production saw — and scored with the live fairness
observatory's ledger + scorecard math (observe/fairness.py). The
rendered table puts the candidates side by side: Jain trajectory,
per-queue delivered share vs regret, starvation totals, preemptions.

This is the evidence the rollout runbook (docs/operations.md, "Rolling
out a fairness policy") asks for before a live flip: `armadactl policy
set` refuses a non-DRF flip without a registered shadow scorecard
unless forced. `armadactl policy ab` is the same harness behind the
CLI.

Exit codes: 0 ok, 2 unusable input (no rounds / undecodable bundle /
foreign target without --allow-foreign / unknown policy).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("traces", nargs="+", help=".atrace bundles to replay")
    ap.add_argument(
        "--policy",
        action="append",
        metavar="POLICY",
        help="candidate policy (repeatable); default: all four kinds",
    )
    ap.add_argument(
        "--solver",
        default="LOCAL",
        help="replay solver spec: LOCAL | hotwindow[:W] | 2x4 (default LOCAL)",
    )
    ap.add_argument(
        "--rounds", type=int, default=None,
        help="cap the number of rounds scored per bundle",
    )
    ap.add_argument(
        "--allow-foreign", action="store_true",
        help="accept bundles recorded on a different host/toolchain",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit the A/B document as one JSON line")
    args = ap.parse_args(argv)

    # Match the production solver configuration (x64 exact costs, healthy
    # backend) BEFORE any jax-touching import: an x64 mismatch against an
    # x64-recorded bundle is a guaranteed target refusal.
    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_healthy_backend()

    from armada_tpu.trace import TraceFormatError
    from armada_tpu.trace.policy_ab import (
        DEFAULT_CANDIDATES,
        ab_compare,
        render_ab,
    )
    from armada_tpu.trace.replayer import TraceTargetMismatch

    try:
        result = ab_compare(
            args.traces,
            args.policy or DEFAULT_CANDIDATES,
            solver=args.solver,
            allow_foreign=args.allow_foreign,
            max_rounds=args.rounds,
        )
    except (OSError, TraceFormatError, TraceTargetMismatch, ValueError) as e:
        print(f"policy_ab: {e}")
        return 2
    if args.json:
        print(json.dumps(result))
    else:
        print(render_ab(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
