"""DCN dryrun: the 2-host x 4-chip multi-process CPU parity run.

Boots 2 real host processes (jax.distributed + gloo) owning a
(2 hosts, 4 chips) mesh, runs the mixed-fleet scenario set (away pools,
a market pool, mixed gangs) through the two-level HierarchicalDist
solve, and checks **bit-exact** equality against the single-device
solve computed independently inside every worker.

Prints exactly ONE machine-readable JSON line on stdout:

  {"ok": true|false, "timed_out": ..., "hosts": 2, "chips": 4,
   "rounds": [...per-round parity/timing...],
   "collectives": {...trace-time DCN/ICI accounting...}, ...}

Exit code 0 iff ok. The wall clock is bounded by --timeout (hard kill).
Wired as a slow-marked test (tests/test_dcn_dryrun.py) so the tier-1
suite stays fast; run directly for the architecture doc's measured DCN
numbers:

  python tools/dcn_dryrun.py --hosts 2 --chips 4 --nodes 512 --jobs 2048
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--chips", type=int, default=4)
    ap.add_argument("--nodes", type=int, default=512)
    ap.add_argument("--jobs", type=int, default=2048)
    ap.add_argument(
        "--timeout",
        type=float,
        default=1500.0,
        help="hard kill for the whole worker fleet, seconds",
    )
    args = ap.parse_args(argv)

    from armada_tpu.parallel.launcher import launch

    result = launch(
        n_hosts=args.hosts,
        n_chips=args.chips,
        n_nodes=args.nodes,
        n_jobs=args.jobs,
        timeout_s=args.timeout,
    )
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
