"""Measure kernel parity of the x64-OFF FALLBACK mode (float32 costs).

The SHIPPED solver configuration enables x64
(utils/platform.enable_exact_costs): every large tensor is explicitly
int32/uint32, so x64 only widens the Q-sized cost vectors to float64 —
measured free — and placement parity with the float64 host oracle is then
exact (the whole x64 parity suite is the proof). This tool quantifies the
OPT-OUT configuration (ARMADA_TPU_X64=0: float32 cost keys), where ties
can resolve differently (kernel.py parity notes). It sweeps the
production-shaped big_scenario populations (and a market-mode sweep
covering the spot-price money path) comparing the float32 kernel against
the float64 host oracle, and prints one JSON line:

  {"scenarios": N, "placement_mismatch_jobs": ..., "sched_set_diffs": ...,
   "max_fair_share_err": ..., "spot_price_max_err": ...}

Run (x64 must stay off — do NOT run under pytest/conftest):
  PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/float32_parity.py
Results are recorded in docs/parity.md.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# Force CPU: the ambient sitecustomize may have registered the axon
# tunnel plugin AND set jax_platforms=axon,cpu at interpreter start —
# env vars alone cannot undo that; _force_cpu deregisters the factories
# and pins the config. (Deliberately NOT ensure_healthy_backend: that
# enables x64, and this tool measures the x64-OFF fallback.)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PYTHONPATH", None)

import numpy as np  # noqa: E402

import jax  # noqa: E402

from armada_tpu.utils.platform import _force_cpu  # noqa: E402

_force_cpu()

assert not jax.config.jax_enable_x64, "run without conftest (x64 must be off)"

from armada_tpu.core.config import PriorityClass, SchedulingConfig  # noqa: E402
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec  # noqa: E402
from armada_tpu.snapshot.round import build_round_snapshot  # noqa: E402
from armada_tpu.solver.kernel import solve_round  # noqa: E402
from armada_tpu.solver.kernel_prep import (  # noqa: E402
    pad_device_round,
    prep_device_round,
)
from armada_tpu.solver.reference import ReferenceSolver  # noqa: E402

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
    ),
)
from test_parity_scale import CFG, big_scenario  # noqa: E402


def compare(cfg, nodes, queues, running, queued, stats, **snap_kw):
    snap = build_round_snapshot(
        cfg, "default", nodes, queues, running, queued, **snap_kw
    )
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    Q = snap.num_queues
    k_nodes = np.asarray(out["assigned_node"])[:J]
    k_sched = np.asarray(out["scheduled_mask"])[:J]
    k_preempt = np.asarray(out["preempted_mask"])[:J]
    stats["scenarios"] += 1
    stats["jobs"] += int(J)
    stats["placement_mismatch_jobs"] += int(
        (oracle.assigned_node != k_nodes).sum()
    )
    stats["sched_set_diffs"] += int((oracle.scheduled_mask != k_sched).sum())
    stats["preempt_set_diffs"] += int((oracle.preempted_mask != k_preempt).sum())
    stats["max_fair_share_err"] = max(
        stats["max_fair_share_err"],
        float(
            np.abs(
                oracle.demand_capped_fair_share
                - np.asarray(out["demand_capped_fair_share"])[:Q]
            ).max()
        ),
    )
    if out.get("spot_price") is not None and oracle.spot_price is not None:
        stats["spot_price_max_err"] = max(
            stats["spot_price_max_err"],
            abs(float(out["spot_price"]) - float(oracle.spot_price)),
        )
    return snap


def market_scenario(seed, n_nodes=64, n_jobs=400):
    """Market mode: bid-ordered scheduling + Vickrey spot price — the
    money-ordering path (solver/pricer.py) where float32 accumulation
    could reorder bids or shift the spot price."""
    rng = np.random.default_rng(seed)
    cfg = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        market_driven=True,
    )
    nodes = [
        NodeSpec(
            id=f"n{i:04d}",
            pool="default",
            total_resources={"cpu": "16", "memory": "64Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(4)]
    bids = np.round(rng.uniform(0.01, 10.0, size=n_jobs), 4)
    queued = [
        JobSpec(
            id=f"j{i:05d}",
            queue=f"q{i % 4}",
            requests={
                "cpu": str(int(rng.choice([1, 2, 4]))),
                "memory": "2Gi",
            },
            submitted_ts=float(i),
            bid_prices={"default": float(bids[i])},
        )
        for i in range(n_jobs)
    ]
    return cfg, nodes, queues, [], queued


def main():
    stats = {
        "x64": bool(jax.config.jax_enable_x64),
        "scenarios": 0,
        "jobs": 0,
        "placement_mismatch_jobs": 0,
        "sched_set_diffs": 0,
        "preempt_set_diffs": 0,
        "max_fair_share_err": 0.0,
        "spot_price_max_err": 0.0,
    }
    for seed in range(4):
        nodes, queues, running, queued = big_scenario(
            seed, n_nodes=128, n_jobs=600
        )
        compare(CFG, nodes, queues, running, queued, stats)
    for seed in range(4):
        nodes, queues, running, queued = big_scenario(
            100 + seed, n_nodes=256, n_jobs=1200
        )
        compare(CFG, nodes, queues, running, queued, stats)
    # Market sweep: per-job bids exercise money ordering + the Vickrey
    # spot-price accumulation.
    for seed in range(4):
        cfg, nodes, queues, running, queued = market_scenario(200 + seed)
        compare(cfg, nodes, queues, running, queued, stats)
    print(json.dumps(stats))


if __name__ == "__main__":
    main()
