"""Pallas kernel probe: preflight -> interpret smoke -> native smoke.

Answers "can this host run the pallas solve kernels, and do they agree
with the lax path?" in one machine-readable JSON line on stdout:

  {"ok": true|false, "platform": "cpu", "native": false,
   "preflight": {...}, "interpret": {...}, "native_smoke": {...}}

Three stages, each recorded even when a later one is skipped:

  1. preflight: platform + relay probe facts (utils/platform) and the
     resolved kernel path for this process — whether `native` would
     demote to interpret mode here and why.
  2. interpret smoke (always): the pallas kernels under interpret=True
     on whatever backend is attached — `fill_take` vs `jnp.lexsort`,
     `winner_reduce` vs host argmin, and a full small mixed-fleet
     solve_round parity sweep lax vs blocked vs pallas (bit-exact or
     the probe fails).
  3. native smoke (only when `native_available()`): the same sweep with
     ARMADA_TPU_KERNEL_PATH=native, compiled for the attached TPU — the
     hardware leg of the tests/test_pallas_parity.py contract.

Exit code 0 iff ok.

  python tools/pallas_probe.py [--nodes 64] [--jobs 256]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _interpret_smoke(n_nodes: int, n_jobs: int) -> dict:
    import numpy as np
    import jax.numpy as jnp

    from armada_tpu.ops import pallas_kernels as pk
    from armada_tpu.parallel.scenarios import mixed_fleet_rounds
    from armada_tpu.solver.kernel import solve_round
    from armada_tpu.solver.kernel_prep import (
        pad_device_round,
        prep_device_round,
    )
    import dataclasses

    out: dict = {}

    # fill_take vs the stable single-key lexsort it replaces.
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 2**40, size=4096, dtype=np.int64))
    want = 256
    take, taken = pk.fill_take(keys, want, nbits=41)
    ref = jnp.lexsort((keys,))[:want]
    out["fill_take_exact"] = bool(np.array_equal(np.asarray(take), np.asarray(ref)))

    # winner_reduce vs the host lexicographic argmin it replaces.
    p = 8
    wkeys = [jnp.asarray(rng.integers(0, 1000, size=p, dtype=np.int32))
             for _ in range(3)]
    found = jnp.asarray(rng.integers(0, 2, size=p, dtype=np.int32)).astype(bool)
    gids = jnp.arange(p, dtype=jnp.int32) + 100
    wgid, wfound = pk.winner_reduce(wkeys, found, gids)
    rows = np.stack([np.asarray(k) for k in wkeys], axis=1)
    alive = np.flatnonzero(np.asarray(found))
    if alive.size:
        # np.lexsort treats the LAST tuple entry as primary; it is
        # stable, so first-index tie-break needs no explicit key.
        order = np.lexsort(tuple(rows[alive].T[::-1]))
        ref_gid = int(np.asarray(gids)[alive[order[0]]])
        ok_w = bool(wfound) and int(wgid) == ref_gid
    else:
        ok_w = not bool(wfound)
    out["winner_reduce_exact"] = ok_w

    # Full-round parity: lax vs blocked vs pallas on the mixed fleet.
    parity = []
    for name, snap in mixed_fleet_rounds(n_nodes, n_jobs):
        dev = pad_device_round(prep_device_round(snap))
        base = {k: np.asarray(v) for k, v in solve_round(dev).items()
                if k not in ("profile", "truncated")}
        for path in ("blocked", "pallas"):
            got = solve_round(dataclasses.replace(dev, kernel_path=path))
            mismatch = [
                k for k, v in base.items()
                if not np.array_equal(np.asarray(got[k]), v, equal_nan=True)
            ]
            parity.append({"round": name, "path": path,
                           "exact": not mismatch, "mismatch": mismatch})
    out["rounds"] = parity
    out["ok"] = (
        out["fill_take_exact"]
        and out["winner_reduce_exact"]
        and all(r["exact"] for r in parity)
    )
    return out


def _native_smoke(n_nodes: int, n_jobs: int) -> dict:
    import numpy as np

    from armada_tpu.parallel.scenarios import mixed_fleet_rounds
    from armada_tpu.solver.kernel import solve_round
    from armada_tpu.solver.kernel_prep import (
        pad_device_round,
        prep_device_round,
    )
    import dataclasses

    parity = []
    for name, snap in mixed_fleet_rounds(n_nodes, n_jobs):
        dev = pad_device_round(prep_device_round(snap))
        base = {k: np.asarray(v) for k, v in solve_round(dev).items()
                if k not in ("profile", "truncated")}
        got = solve_round(dataclasses.replace(dev, kernel_path="native"))
        mismatch = [
            k for k, v in base.items()
            if not np.array_equal(np.asarray(got[k]), v, equal_nan=True)
        ]
        parity.append({"round": name, "exact": not mismatch,
                       "mismatch": mismatch})
    return {"rounds": parity, "ok": all(r["exact"] for r in parity)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=64)
    ap.add_argument("--jobs", type=int, default=256)
    args = ap.parse_args(argv)

    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_healthy_backend()

    import jax

    from armada_tpu.ops import pallas_kernels as pk
    from armada_tpu.utils import platform as plat

    result: dict = {
        "platform": jax.default_backend(),
        "native": pk.native_available(),
        "preflight": {
            "probe": plat.last_probe_report,
            "resolved_native": pk.resolve_kernel_path("native"),
            "pallas_importable": pk.pl is not None,
        },
    }
    try:
        result["interpret"] = _interpret_smoke(args.nodes, args.jobs)
        ok = result["interpret"]["ok"]
        if result["native"]:
            result["native_smoke"] = _native_smoke(args.nodes, args.jobs)
            ok = ok and result["native_smoke"]["ok"]
        result["ok"] = bool(ok)
    except Exception as e:  # noqa: BLE001 - the JSON line IS the report
        result["ok"] = False
        result["error"] = f"{e.__class__.__name__}: {e}"
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
