"""Bench regression gate: compare a current bench result against the
latest checked-in BENCH_r*.json artifact and fail on regression.

    python tools/bench_gate.py --current out.json [--threshold 1.15]
    python bench.py | tail -1 | python tools/bench_gate.py --current -

Accepts either shape on both sides (the artifact schema drifted across
rounds — BENCH_r03.json has no `parsed` block at all):

  - a driver artifact: {"rc": ..., "tail": ..., "parsed": {...}}
    (falls back to parsing the LAST JSON line of `tail` when `parsed`
    is absent);
  - a raw bench stdout line: {"metric": ..., "value": ..., "extra": ...}.

Gated metrics: the warm headline cycle, tracking_100k and burst_50k
cycle times, plus the headline cycle's per-segment medians — pass1 and
gather seconds from `extra.segments` (the median-representative warm
cycle's solve profile), so a regression INSIDE the solve (a pass-1
slowdown hidden by a faster host phase, a gather/scatter blowup from a
bad window) gates even when the end-to-end number still squeaks under
the threshold. A metric regresses when current > baseline * threshold;
a metric missing on either side is reported but never gates (old
artifacts predate burst_50k and the segment profile).

Some gates are ABSOLUTE (need no baseline): the round admission
firewall's host-side invariant sweep (extra.validate_s, timed by
bench.py outside the measured cycle) must cost under 5% of the
headline solve time — the firewall runs before every committed round,
so its cost taxes the whole control loop — and, when
--residency-budget-mb is passed, the warm headline cycle's booked
upload (extra.transfer.bytes_up) must stay under that many MB: with
the round device-resident (snapshot/residency.py) a warm cycle uploads
only the delta, so blowing the budget means residency silently
disengaged or the delta path fell back to full re-uploads. Symmetric
on the download side, --readback-budget-mb caps the warm cycle's
booked result readback (extra.transfer.bytes_down): with
solve_round(readback_rows=...) trimming the d2h to the unpadded
decision prefix, blowing it means the trim disengaged. Exits 1 on
regression, 2 when no comparable baseline exists, 0 otherwise.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_artifact(doc: dict) -> dict | None:
    """The bench result dict out of either schema, or None."""
    if not isinstance(doc, dict):
        return None
    if "value" in doc or "extra" in doc:  # raw bench stdout line
        return doc
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and parsed.get("ok", True):
        return parsed
    # Old schema (r03 and earlier): no parsed block — recover the bench
    # line from the captured tail.
    tail = doc.get("tail") or ""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


GATED = ("warm", "tracking", "burst", "pass1", "gather")
# Round-observatory metrics (extra.transfer, absent before the
# observatory round): bytes are deterministic counts gated by the same
# threshold factor; the warm-cycle compile count is gated on ANY
# increase — zero compiles IS the warm steady state, so one compile
# sneaking into a warm cycle is a regression however fast it was.
GATED_TRANSFER = ("bytes_up", "bytes_down", "compiles")
# Absolute ceiling on the admission firewall's share of solve time.
VALIDATE_FRAC_LIMIT = 0.05


def extract_metrics(result: dict | None) -> dict:
    """{name: value|None} for every gated metric from a bench result
    dict; tolerant of every historical shape (pass1/gather come from
    the headline config's extra.segments solve profile, absent before
    the hot-window round; bytes_up/bytes_down/compiles from
    extra.transfer, absent before the observatory round)."""
    out = {name: None for name in GATED + GATED_TRANSFER}
    if not isinstance(result, dict):
        return out
    if isinstance(result.get("value"), (int, float)):
        out["warm"] = float(result["value"])
    extra = result.get("extra")
    if isinstance(extra, dict):
        for key, name in (("tracking_100k", "tracking"), ("burst_50k", "burst")):
            sub = extra.get(key)
            if isinstance(sub, dict) and isinstance(
                sub.get("cycle_s"), (int, float)
            ):
                out[name] = float(sub["cycle_s"])
        segments = extra.get("segments")
        if isinstance(segments, dict):
            for seg, name in (("pass1_s", "pass1"), ("gather_s", "gather")):
                if isinstance(segments.get(seg), (int, float)):
                    out[name] = float(segments[seg])
        transfer = extra.get("transfer")
        if isinstance(transfer, dict):
            for key in ("bytes_up", "bytes_down"):
                if isinstance(transfer.get(key), (int, float)):
                    out[key] = float(transfer[key])
            compiles = transfer.get("compiles")
            if isinstance(compiles, dict) and isinstance(
                compiles.get("compiles"), (int, float)
            ):
                out["compiles"] = float(compiles["compiles"])
    return out


def gate(current: dict, baseline: dict, threshold: float) -> tuple[list, list]:
    """(regressions, notes) comparing extract_metrics dicts. A metric
    regresses when current > baseline * threshold; the warm compile
    count regresses on any increase over the baseline."""
    regressions, notes = [], []
    for name in GATED:
        cur, base = current.get(name), baseline.get(name)
        if cur is None or base is None:
            notes.append(f"{name}: not comparable (current={cur} baseline={base})")
            continue
        # Sub-ms segment baselines are scheduler noise, not signal: a
        # 0.4ms gather doubling to 0.9ms must not fail the gate.
        limit = max(base, 0.01) * threshold
        line = f"{name}: current {cur:.4f}s vs baseline {base:.4f}s (limit {limit:.4f}s)"
        if cur > limit:
            regressions.append(line)
        else:
            notes.append("OK " + line)
    for name in GATED_TRANSFER:
        cur, base = current.get(name), baseline.get(name)
        if cur is None or base is None:
            notes.append(f"{name}: not comparable (current={cur} baseline={base})")
            continue
        if name == "compiles":
            line = f"compiles: current {cur:.0f} vs baseline {base:.0f} (any increase gates)"
            if cur > base:
                regressions.append(line)
            else:
                notes.append("OK " + line)
            continue
        limit = max(base, 1.0) * threshold
        line = f"{name}: current {cur:.0f}B vs baseline {base:.0f}B (limit {limit:.0f}B)"
        if cur > limit:
            regressions.append(line)
        else:
            notes.append("OK " + line)
    return regressions, notes


def absolute_gate(result: dict | None) -> tuple[list, list]:
    """(regressions, notes) for baseline-free gates on the CURRENT
    artifact alone. validate_frac: extra.validate_s over extra.solve_s
    must stay under VALIDATE_FRAC_LIMIT. Missing fields never gate
    (artifacts predate the firewall round)."""
    regressions, notes = [], []
    extra = result.get("extra") if isinstance(result, dict) else None
    if not isinstance(extra, dict):
        return regressions, notes
    val, solve = extra.get("validate_s"), extra.get("solve_s")
    if not isinstance(val, (int, float)) or not isinstance(
        solve, (int, float)
    ) or solve <= 0:
        notes.append(
            "validate_frac: not comparable "
            f"(validate_s={val} solve_s={solve})"
        )
        return regressions, notes
    frac = val / solve
    line = (
        f"validate_frac: validate {val:.4f}s / solve {solve:.4f}s = "
        f"{frac:.3f} (limit {VALIDATE_FRAC_LIMIT})"
    )
    if frac > VALIDATE_FRAC_LIMIT:
        regressions.append(line)
    else:
        notes.append("OK " + line)
    return regressions, notes


def residency_gate(result: dict | None, budget_mb: float | None) -> tuple[list, list]:
    """(regressions, notes) for the absolute residency budget. Only
    active when --residency-budget-mb is passed; then a current artifact
    MISSING extra.transfer.bytes_up gates too — the flag is an explicit
    assertion that the warm upload is measured and delta-sized, so an
    artifact that cannot prove it must not read as green."""
    regressions, notes = [], []
    if budget_mb is None:
        return regressions, notes
    extra = result.get("extra") if isinstance(result, dict) else None
    transfer = extra.get("transfer") if isinstance(extra, dict) else None
    up = transfer.get("bytes_up") if isinstance(transfer, dict) else None
    residency = extra.get("residency") if isinstance(extra, dict) else None
    mode = residency.get("mode") if isinstance(residency, dict) else None
    if not isinstance(up, (int, float)):
        regressions.append(
            "residency: current artifact has no extra.transfer.bytes_up "
            f"(budget {budget_mb:g} MB asserted)"
        )
        return regressions, notes
    line = (
        f"residency: warm bytes_up {up / 1e6:.1f}MB vs budget "
        f"{budget_mb:g}MB" + (f" (mode={mode})" if mode else "")
    )
    if up > budget_mb * 1e6:
        regressions.append(line)
    else:
        notes.append("OK " + line)
    return regressions, notes


def readback_gate(result: dict | None, budget_mb: float | None) -> tuple[list, list]:
    """(regressions, notes) for the absolute round-readback budget.
    Only active when --readback-budget-mb is passed; then the warm
    headline cycle's booked result download (extra.transfer.bytes_down)
    must stay under that many MB — with solve_round(readback_rows=...)
    trimming the d2h to the unpadded decision prefix, blowing the budget
    means the trim silently disengaged and warm cycles are paying the
    full padded-J readback again. Like the residency gate, an artifact
    MISSING the field gates too: the flag asserts the download is
    measured and prefix-sized, so an artifact that cannot prove it must
    not read as green."""
    regressions, notes = [], []
    if budget_mb is None:
        return regressions, notes
    extra = result.get("extra") if isinstance(result, dict) else None
    transfer = extra.get("transfer") if isinstance(extra, dict) else None
    down = transfer.get("bytes_down") if isinstance(transfer, dict) else None
    if not isinstance(down, (int, float)):
        regressions.append(
            "readback: current artifact has no extra.transfer.bytes_down "
            f"(budget {budget_mb:g} MB asserted)"
        )
        return regressions, notes
    line = (
        f"readback: warm bytes_down {down / 1e6:.1f}MB vs budget "
        f"{budget_mb:g}MB"
    )
    if down > budget_mb * 1e6:
        regressions.append(line)
    else:
        notes.append("OK " + line)
    return regressions, notes


def _round_num(path: str) -> int:
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def latest_baseline(search_dir: str) -> tuple[str | None, dict]:
    """Newest BENCH_r*.json with extractable metrics (skips artifacts
    no schema recovers anything from rather than gating on nothing)."""
    for path in sorted(
        glob.glob(os.path.join(search_dir, "BENCH_r*.json")),
        key=_round_num,
        reverse=True,
    ):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        metrics = extract_metrics(parse_artifact(doc))
        if any(v is not None for v in metrics.values()):
            return path, metrics
    return None, {name: None for name in GATED}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--current", required=True,
                    help="bench result JSON file, or - for stdin")
    ap.add_argument("--baseline-dir", default=REPO)
    ap.add_argument("--threshold", type=float, default=1.15,
                    help="regression factor (1.15 = allow 15%% slower)")
    ap.add_argument("--residency-budget-mb", type=float, default=None,
                    help="absolute ceiling (MB) on the warm headline "
                    "cycle's extra.transfer.bytes_up — asserts the "
                    "device-resident delta path carried the round")
    ap.add_argument("--readback-budget-mb", type=float, default=None,
                    help="absolute ceiling (MB) on the warm headline "
                    "cycle's extra.transfer.bytes_down — asserts the "
                    "readback_rows prefix trim carried the download")
    args = ap.parse_args(argv)

    raw = (
        sys.stdin.read()
        if args.current == "-"
        else open(args.current).read()
    )
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as e:
        print(f"bench_gate: current result is not JSON: {e}")
        return 2
    current = extract_metrics(parse_artifact(doc))
    if all(v is None for v in current.values()):
        # A crashed/failed bench (ok=false, value null) must not read as
        # a green gate: nothing on the current side is comparable.
        print("bench_gate: current result carries no extractable metrics")
        return 2
    base_path, baseline = latest_baseline(args.baseline_dir)
    if base_path is None:
        print("bench_gate: no usable BENCH_r*.json baseline found")
        return 2
    regressions, notes = gate(current, baseline, args.threshold)
    abs_regressions, abs_notes = absolute_gate(parse_artifact(doc))
    regressions += abs_regressions
    notes += abs_notes
    res_regressions, res_notes = residency_gate(
        parse_artifact(doc), args.residency_budget_mb
    )
    regressions += res_regressions
    notes += res_notes
    rb_regressions, rb_notes = readback_gate(
        parse_artifact(doc), args.readback_budget_mb
    )
    regressions += rb_regressions
    notes += rb_notes
    print(f"baseline: {os.path.basename(base_path)}")
    for line in notes:
        print(line)
    for line in regressions:
        print("REGRESSION " + line)
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
