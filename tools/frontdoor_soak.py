"""Front-door soak: streaming submit→round→lease under chaos + SLO gate.

Drives a STREAMING workload (jobs are generated on the fly, never
pre-built — the harness scales to 10M jobs across thousands of tenants
without holding the workload in memory) through the full control-plane
path: per-tenant admission → jobset-keyed shard WAL ack → per-shard
exactly-once ingest → scheduling rounds → fake-executor leases, on a
virtual clock, with a seeded chaos plan tearing shard WAL appends
(torn_log_write), severing shard ingesters (network_partition) and
crash-looping them mid-batch (executor_crash) — plus a designated FLOOD
TENANT that submits far past its rate so tenant-aware shedding is
exercised every run.

After the run the gate verifies, per seed:

  - ZERO LOST ACKS: every acknowledged job id appears in the main event
    log and in the jobdb;
  - ZERO DOUBLE-APPLIES: no job id appears in the log twice (the
    exactly-once markers held through every injected crash);
  - jobdb `assert_valid` (the split-brain invariants);
  - every acked job reached a TERMINAL state (chaos delays work, never
    loses it);
  - shed traffic carried a positive retry-after (clients back off
    deliberately, they do not time out);
  - submit p99 (wall clock through admission + durable WAL ack) under
    the SLO;
  - max shard ingest lag under the SLO.

Any breach exits nonzero — the bench_gate analogue for front-door scale.
`--inject-loss` deliberately drops one acked WAL entry during delivery
(the fault the gate exists to catch) and MUST trip it.

Usage:
  python tools/frontdoor_soak.py                   # committed config
  python tools/frontdoor_soak.py --seeds 2 --jobs 2000 --tenants 50
  python tools/frontdoor_soak.py --jobs 10000000 --tenants 5000  # full
  python tools/frontdoor_soak.py --inject-loss     # must exit nonzero

Exit code 0 = every seed met the SLO; 1 = breach; prints one JSON line
per seed plus a summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time as _time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The committed soak config: the SLO gate in CI runs exactly this.
DEFAULTS = {
    "jobs": 4000,
    "tenants": 100,
    "shards": 4,
    "executors": 2,
    "nodes_per_executor": 16,
    "node_cpu": "16",
    "cycle_interval_s": 10.0,
    "job_runtime_s": 30.0,
    "batch": 20,          # jobs per submit RPC
    # The tenant flood: for a mid-run window one tenant attempts
    # flood_x TIMES its sustained token-bucket rate (absolute pressure,
    # not a share of traffic — at small scales a traffic share can sit
    # under the rate limit and never shed).
    "flood_x": 3.0,
    "tenant_rate": 10.0,  # jobs/s/tenant — generous for the steady tenants
    "tenant_burst": 40.0,
    "global_rate": 5000.0,
    "global_burst": 10000.0,
    "overload_rate": 200.0,
    "max_ingest_lag_events": 20000,
    "slo": {
        # Wall clock through admission + durable shard-WAL fsync ack.
        "submit_p99_s": 0.25,
        # Acked-but-undelivered WAL records (batches) on any one shard
        # at any instant — generous headroom over the partition-window
        # backlog the committed chaos plan produces (~tens).
        "max_shard_lag_events": 1000,
    },
}


def build_fault_plan(seed: int, duration: float, shards: int):
    """Seeded shard-targeted chaos over the soak horizon: a torn WAL
    append per shard, one mid-run ingester partition, and a bounded
    crash budget that kills delivery mid-batch a few times."""
    from armada_tpu.services.chaos import FaultPlan, FaultSpec

    faults = []
    for i in range(shards):
        faults.append(
            FaultSpec(
                "torn_log_write", f"shard-{i}",
                start=duration * (0.1 + 0.15 * (i % 3)) + seed % 7,
                duration=duration * 0.5, count=2, param=0.4 + 0.1 * i,
            )
        )
    # One shard goes dark mid-run and heals: lag grows, nothing is lost.
    faults.append(
        FaultSpec(
            "network_partition", f"shard-{seed % shards}",
            start=duration * 0.35 + (seed % 5) * 3.0,
            duration=duration * 0.15,
        )
    )
    # Crash-restart another shard's ingester mid-batch a few times.
    faults.append(
        FaultSpec(
            "executor_crash", f"shard-{(seed + 1) % shards}",
            start=duration * 0.55 + (seed % 5) * 3.0,
            duration=duration * 0.3, count=3,
        )
    )
    faults.sort(key=lambda f: (f.start, f.kind, f.target))
    return FaultPlan(faults, seed=seed)


def run_soak(seed: int, cfg: dict, inject_loss: bool = False,
             verbose: bool = False, slos=None) -> dict:
    """One seeded soak; returns the gate document (breaches list
    included). Raises nothing for SLO breaches — the caller gates.

    `slos`: an iterable of core.config.SLOSpec (or True for the soak's
    defaults) attaches a services/slo.SLOTracker to the submit path —
    every submit (admitted, shed or expired) feeds the
    frontdoor_submit_seconds signal, the tracker's evaluate() verdict
    joins the breach list, and the raw observation stream lands in the
    doc under "slo" for offline re-evaluation by tools/slo_gate.py."""
    import numpy as np

    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.events.model import SubmitJob
    from armada_tpu.frontdoor import (
        AdmissionError,
        DeadlineExpired,
        FrontDoor,
        TenantAdmission,
    )
    from armada_tpu.services.backpressure import StoreHealthMonitor
    from armada_tpu.services.chaos import VirtualClock
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    rng = np.random.default_rng(seed)
    n_jobs = int(cfg["jobs"])
    n_tenants = int(cfg["tenants"])
    cycle = float(cfg["cycle_interval_s"])
    batch = int(cfg["batch"])
    # Submission horizon: spread jobs over enough virtual time that the
    # fleet can roughly keep up (cap the queued backlog, stream through).
    runtime = float(cfg["job_runtime_s"])
    capacity = (
        int(cfg["executors"]) * int(cfg["nodes_per_executor"])
        * int(cfg["node_cpu"])
    )
    horizon = max(10 * cycle, n_jobs * runtime / max(1, capacity) * 1.3)
    plan = build_fault_plan(seed, horizon, int(cfg["shards"]))
    clock = VirtualClock()
    config = SchedulingConfig(
        enable_assertions=n_jobs <= 20_000,
        executor_timeout_s=20 * cycle,
        terminal_job_retention_s=4 * horizon,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    store_gate = StoreHealthMonitor(
        log, max_ingest_lag_events=int(cfg["max_ingest_lag_events"]),
        check_interval_s=0.0,
    )
    weights = {f"t{i:04d}": 1.0 for i in range(n_tenants)}
    admission = TenantAdmission(
        tenant_rate=float(cfg["tenant_rate"]),
        tenant_burst=float(cfg["tenant_burst"]),
        global_rate=float(cfg["global_rate"]),
        global_burst=float(cfg["global_burst"]),
        overload_rate=float(cfg["overload_rate"]),
        downstream=store_gate,
        quota_of=weights.get,
    )
    tmp = tempfile.TemporaryDirectory(prefix=f"frontdoor-soak-{seed}-")
    fd = FrontDoor(
        log, num_shards=int(cfg["shards"]), directory=tmp.name,
        admission=admission, fault_plan=plan, clock=clock,
    )
    store_gate.add_lag_source("scheduler-ingester",
                              lambda: max(0, log.end_offset - sched.ingester.cursor))
    store_gate.add_lag_source("frontdoor", fd.max_lag)
    tracker = None
    if slos:
        from armada_tpu.core.config import SLOSpec
        from armada_tpu.services.slo import SLOTracker

        specs = (
            (
                # The soak's default: the committed submit-p99 SLO as a
                # declared objective (the hand-rolled p99 check below
                # stays — the tracker adds burn-rate semantics and the
                # offline-reevaluable observation stream).
                SLOSpec(
                    name="frontdoor-p99",
                    signal="frontdoor_submit_seconds",
                    threshold_s=float(cfg["slo"]["submit_p99_s"]),
                    objective=0.99,
                ),
            )
            if slos is True
            else tuple(slos)
        )
        # The retained raw stream is bounded (oldest dropped): seed docs
        # stay printable at full-scale soaks while committed-config runs
        # export every observation for tools/slo_gate.py.
        tracker = SLOTracker(specs, keep_observations=50_000)
    submit = SubmitService(config, log, scheduler=sched, frontdoor=fd,
                           slo=tracker)
    for tenant in weights:
        submit.create_queue(QueueSpec(tenant))
    executors = [
        FakeExecutor(
            f"soak-ex{i}", log, sched,
            nodes=make_nodes(
                f"soak-ex{i}", count=int(cfg["nodes_per_executor"]),
                cpu=cfg["node_cpu"], memory="512Gi",
            ),
            runtime_for=lambda job_id: runtime,
        )
        for i in range(int(cfg["executors"]))
    ]
    if inject_loss:
        # The seeded fault the gate exists to catch: shard 0 silently
        # DROPS one acked WAL entry during delivery.
        dropped = []

        def lossy(shard, entry):
            if not dropped and entry.offset == 1:
                dropped.append(entry.offset)
                return True
            return False

        fd.shards[0].crash_hook = lossy

    tenants = sorted(weights)
    flood = tenants[seed % n_tenants]
    acked: set[str] = set()
    latencies: list[float] = []
    shed = expired = 0
    min_retry_after = float("inf")
    max_lag_seen = 0
    jid = 0
    submitted_target = n_jobs
    t = 0.0
    sub_rate = n_jobs / (horizon * 0.75)  # jobs per virtual second

    def submit_batch(tenant: str, count: int, now: float):
        nonlocal jid, shed, expired, min_retry_after
        jobs = []
        for _ in range(count):
            jobs.append(JobSpec(
                id=f"s{seed}-{jid:08d}", queue=tenant,
                jobset=f"{tenant}-js{jid % 7}",
                requests={"cpu": "1", "memory": "1Gi"},
            ))
            jid += 1
        started = _time.perf_counter()
        try:
            ids = submit.submit(tenant, jobs[0].jobset, jobs, now=now,
                                deadline_ts=now + 5 * cycle)
        except AdmissionError as e:
            shed += count
            min_retry_after = min(min_retry_after, e.retry_after_s)
            return
        except DeadlineExpired:
            expired += count
            return
        latencies.append(_time.perf_counter() - started)
        acked.update(ids)

    flood_window = (0.25 * horizon, 0.55 * horizon)
    flood_due = max(batch, int(
        float(cfg["flood_x"]) * float(cfg["tenant_rate"]) * cycle
    ))
    steady_sent = 0
    while True:
        clock.now = t
        due = int(sub_rate * cycle)
        remaining = submitted_target - steady_sent
        if remaining > 0:
            # The steady stream: the budgeted workload spread across
            # rotating tenants. Attempts count against the budget
            # whether admitted or shed, so the stream spans the whole
            # horizon and the fault windows land on live traffic.
            wave = min(due, remaining)
            spent = 0
            while spent < wave:
                tenant = tenants[int(rng.integers(n_tenants))]
                count = min(batch, wave - spent)
                submit_batch(tenant, count, t)
                spent += count
            steady_sent += spent
        if flood_window[0] <= t < flood_window[1]:
            # The tenant flood: flood_x times the flood tenant's
            # sustained rate for a bounded mid-run window — far past its
            # bucket, so tenant-aware shedding engages EVERY run while
            # its neighbours' buckets stay untouched. Flood attempts
            # ride on top of the steady budget (shed traffic is
            # pressure, not workload).
            for off in range(0, flood_due, batch):
                submit_batch(flood, min(batch, flood_due - off), t)
        fd.pump(now=t)
        max_lag_seen = max(max_lag_seen, fd.max_lag())
        for ex in executors:
            ex.tick(t)
        sched.cycle(now=t)
        for ex in executors:
            ex.tick(t)
        txn = sched.jobdb.read_txn()
        terminal = sum(1 for j in txn.all_jobs() if j.state.terminal)
        done_submitting = (
            steady_sent >= submitted_target or t > horizon * 0.75
        )
        if done_submitting and fd.max_lag() == 0 and terminal >= len(acked):
            break
        if t > 6 * horizon:
            break  # safety: gate will flag stuck work
        t += cycle

    # ---- verification sweep ----
    breaches = []
    submit_counts: dict[str, int] = {}
    for entry in log.read(0, 10 ** 9):
        for event in entry.sequence.events:
            if isinstance(event, SubmitJob):
                jid_ = event.job.id
                submit_counts[jid_] = submit_counts.get(jid_, 0) + 1
    duplicates = sorted(j for j, c in submit_counts.items() if c > 1)
    lost = sorted(j for j in acked if j not in submit_counts)
    if duplicates:
        breaches.append(
            f"{len(duplicates)} acked submits double-applied "
            f"(first: {duplicates[0]})"
        )
    if lost:
        breaches.append(
            f"{len(lost)} acked submits lost (first: {lost[0]})"
        )
    txn = sched.jobdb.read_txn()
    try:
        txn.assert_valid()
    except AssertionError as e:
        breaches.append(f"jobdb invariant violation: {e}")
    non_terminal = sorted(
        j for j in acked
        if (job := txn.get(j)) is None or not job.state.terminal
    )
    if non_terminal:
        breaches.append(
            f"{len(non_terminal)} acked jobs never reached a terminal "
            f"state (first: {non_terminal[0]})"
        )
    if shed and min_retry_after <= 0:
        breaches.append("shed traffic carried no positive retry-after")
    if admission.shed.get(flood, 0) == 0:
        breaches.append(
            f"flood tenant {flood} was never shed — tenant-aware "
            "admission did not engage"
        )
    latencies.sort()
    p99 = latencies[int(0.99 * (len(latencies) - 1))] if latencies else 0.0
    slo = cfg["slo"]
    if p99 > float(slo["submit_p99_s"]):
        breaches.append(
            f"submit p99 {p99 * 1e3:.1f}ms over SLO "
            f"{float(slo['submit_p99_s']) * 1e3:.0f}ms"
        )
    if max_lag_seen > int(slo["max_shard_lag_events"]):
        breaches.append(
            f"max shard lag {max_lag_seen} over SLO "
            f"{slo['max_shard_lag_events']}"
        )
    slo_block = None
    if tracker is not None:
        verdict = tracker.evaluate(now=t)
        breaches += [f"slo: {b}" for b in verdict["breaches"]]
        slo_block = {
            "ok": verdict["ok"],
            "breaches": verdict["breaches"],
            "slos": [
                {k: s[k] for k in ("name", "observed", "good", "bad",
                                   "compliance")}
                for s in verdict["slos"]
            ],
            "observations": tracker.observations(),
        }
    doc = {
        "seed": seed,
        "acked": len(acked),
        "shed": shed,
        "expired": expired,
        "flood_tenant": flood,
        "flood_shed": admission.shed.get(flood, 0),
        "submit_p99_ms": round(p99 * 1e3, 3),
        "max_shard_lag": max_lag_seen,
        "duplicates": len(duplicates),
        "lost": len(lost),
        "faults_fired": plan.fired(),
        "shard_restarts": sum(s.restarts for s in fd.shards),
        "dups_suppressed": sum(s.duplicates_suppressed for s in fd.shards),
        "wal_crashes": sum(
            getattr(s.wal, "crashes", 0) for s in fd.shards
        ),
        "makespan": round(t, 1),
        "breaches": breaches,
    }
    if slo_block is not None:
        doc["slo"] = slo_block
    fd.close()
    tmp.cleanup()
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="frontdoor-soak")
    ap.add_argument("--seeds", type=int, default=1,
                    help="seeded runs (seed = 0..N-1)")
    ap.add_argument("--jobs", type=int, default=None)
    ap.add_argument("--tenants", type=int, default=None)
    ap.add_argument("--shards", type=int, default=None)
    ap.add_argument("--inject-loss", action="store_true",
                    help="drop one acked WAL entry (the gate MUST trip)")
    ap.add_argument("--slo", action="store_true",
                    help="attach a services/slo.SLOTracker to the submit "
                    "path: declared-SLO breaches (burn-rate semantics) "
                    "join the gate, and each seed doc carries the raw "
                    "observation stream for tools/slo_gate.py")
    ap.add_argument("--slo-threshold", type=float, default=None,
                    help="override the tracked submit-latency SLO "
                    "threshold in seconds (with --slo; a deliberately "
                    "tiny value proves the gate trips)")
    ap.add_argument("--out", default=None,
                    help="write a bench-style artifact with the "
                         "extra.frontdoor block (tools/bench_trend.py)")
    args = ap.parse_args(argv)
    cfg = dict(DEFAULTS)
    for key in ("jobs", "tenants", "shards"):
        value = getattr(args, key)
        if value is not None:
            cfg[key] = value

    slos = None
    if args.slo:
        if args.slo_threshold is not None:
            from armada_tpu.core.config import SLOSpec

            slos = (
                SLOSpec(
                    name="frontdoor-p99",
                    signal="frontdoor_submit_seconds",
                    threshold_s=args.slo_threshold,
                    objective=0.99,
                ),
            )
        else:
            slos = True
    failures = 0
    docs = []
    for seed in range(args.seeds):
        doc = run_soak(seed, cfg, inject_loss=args.inject_loss, slos=slos)
        docs.append(doc)
        if doc["breaches"]:
            failures += 1
        print(json.dumps(doc))
    worst_p99 = max((d["submit_p99_ms"] for d in docs), default=0.0)
    summary = {
        "seeds": args.seeds,
        "failures": failures,
        "submit_p99_ms": worst_p99,
        "max_shard_lag": max((d["max_shard_lag"] for d in docs), default=0),
        "shed": sum(d["shed"] for d in docs),
        "slo": cfg["slo"],
    }
    print(json.dumps(summary))
    if args.out:
        artifact = {
            "metric": "frontdoor_soak",
            "value": worst_p99 / 1e3,
            "extra": {
                "frontdoor": {
                    "p99_ms": worst_p99,
                    "max_lag": summary["max_shard_lag"],
                    "shed": summary["shed"],
                    "seeds": args.seeds,
                    "ok": failures == 0,
                }
            },
        }
        with open(args.out, "w") as f:
            json.dump(artifact, f)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
