"""Shadow-solve divergence gate: replay recorded traces, fail on drift.

The solver-level analogue of tools/bench_gate.py: run a candidate
kernel over a flight-recorder corpus (one or more `.atrace` bundles,
armada_tpu/trace) and exit non-zero when any replayed round's decision
stream diverges from the recorded one.

    python tools/replay_gate.py tests/fixtures/sim_steady.atrace
    python tools/replay_gate.py trace.atrace --solver LOCAL --solver 2x4 \
        --solver hotwindow:4
    python tools/replay_gate.py trace.atrace --perturb tiebreak  # must fail

Divergences classify as `placement` (any decision array differs —
placements, evictions, priorities, fair shares, spot price),
`loop_stream` (same decisions, different pass-1 loop count),
`profile_regression` (replay wall clock beyond --profile-threshold x
the recorded solve time; off by default — wall clocks only compare on
one host), and `retrace` (XLA traced/compiled during a round whose
shape signature was already replayed under that solver — a warm cycle
must dispatch cached executables; disable with --no-retrace-check).
`--perturb tiebreak` injects a deliberately-buggy candidate
(reversed node tie-break ranking) to prove the gate trips.

A bundle recorded on a different target (host CPU features / XLA
toolchain / x64 mode) REFUSES to replay with a clear error; pass
--allow-foreign for x64-recorded traces, whose exact decisions are
host-independent. Exit codes: 0 clean, 1 divergences, 2 unusable
(no rounds, undecodable bundle, target mismatch).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("traces", nargs="+", help=".atrace bundles to replay")
    ap.add_argument(
        "--solver",
        action="append",
        default=None,
        help="solver spec to replay under: LOCAL, hotwindow[:W], or a mesh "
        'spelling like "2x4" / "8" (repeatable; default LOCAL)',
    )
    ap.add_argument("--max-rounds", type=int, default=0,
                    help="replay at most N rounds per bundle (0 = all)")
    ap.add_argument(
        "--profile-threshold", type=float, default=0.0,
        help="flag profile_regression when replay wall clock exceeds this "
        "factor of the recorded solve time (0 = off; same-host runs only)",
    )
    ap.add_argument("--perturb", choices=("tiebreak",), default=None,
                    help="inject a deliberately-buggy candidate kernel")
    ap.add_argument("--allow-foreign", action="store_true",
                    help="replay a bundle recorded on a different host "
                    "(sound only for x64-recorded traces)")
    ap.add_argument("--no-retrace-check", action="store_true",
                    help="skip the warm-shape retrace audit (e.g. when "
                    "deliberately replaying with cold jit caches "
                    "cleared between rounds)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON line")
    args = ap.parse_args(argv)

    # Match the production solver configuration (x64 exact costs, healthy
    # backend) BEFORE any jax-touching import: an x64 mismatch against an
    # x64-recorded bundle is a guaranteed target refusal.
    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_healthy_backend()

    from armada_tpu.trace import (
        TraceFormatError,
        TraceTargetMismatch,
        load_trace,
        replay_trace,
    )

    solvers = args.solver or ["LOCAL"]
    reports = []
    total_rounds = 0
    by_kind: dict[str, int] = {}
    for path in args.traces:
        try:
            trace = load_trace(path)
        except (OSError, TraceFormatError) as e:
            print(f"replay_gate: cannot load {path}: {e}")
            return 2
        try:
            report = replay_trace(
                trace,
                solvers=solvers,
                max_rounds=args.max_rounds or None,
                profile_threshold=args.profile_threshold or None,
                perturb=args.perturb,
                allow_foreign=args.allow_foreign,
                flag_retraces=not args.no_retrace_check,
                log=lambda msg: print(f"{os.path.basename(path)}: {msg}"),
            )
        except TraceTargetMismatch as e:
            print(f"replay_gate: {path}: {e}")
            return 2
        except TraceFormatError as e:
            print(f"replay_gate: {path}: {e}")
            return 2
        reports.append(report)
        total_rounds += report["rounds"]
        for kind, n in report["divergences"].items():
            by_kind[kind] = by_kind.get(kind, 0) + n

    if total_rounds == 0:
        print("replay_gate: no replayable rounds in the given bundles "
              "(all truncated or empty)")
        return 2
    summary = {
        "bundles": len(reports),
        "rounds": total_rounds,
        "solvers": solvers,
        "divergences": by_kind,
        "ok": not by_kind,
    }
    if args.json:
        print(json.dumps({"summary": summary, "reports": reports}))
    else:
        verdict = "OK" if summary["ok"] else f"DIVERGED {by_kind}"
        print(
            f"replay_gate: {total_rounds} round(s) x {len(solvers)} "
            f"solver(s) across {len(reports)} bundle(s): {verdict}"
        )
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
